"""Cloud storage fetchers (serve/cloudstorage.py) against LOCAL in-process
emulators of the real wire protocols — S3 REST XML (ListObjectsV2 + SigV4
verification), GCS JSON API (list + alt=media, STORAGE_EMULATOR_HOST), and a
flaky HTTP server that drops connections mid-stream to prove Range resume.

Reference analog: KServe storage-initializer scheme handlers (SURVEY.md §2.2
storage row); the reference tests these against moto/fake-gcs — same idea,
first-party emulators here (zero egress, no moto installed).
"""

from __future__ import annotations

import hashlib
import threading
import urllib.parse
from xml.sax.saxutils import escape

import pytest
from aiohttp import web

from kubeflow_tpu.serve import cloudstorage, storage


# --------------------------------------------------------------------------- #
# in-process emulator harness
# --------------------------------------------------------------------------- #


class _Server:
    """Run an aiohttp app on a thread-owned loop; .port after start()."""

    def __init__(self, app: web.Application):
        self.app = app
        self.port: int | None = None
        self._started = threading.Event()
        self._stop = None
        self._thread = None

    def __enter__(self):
        import asyncio

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._stop = loop.create_future()
            runner = web.AppRunner(self.app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "127.0.0.1", 0)
            loop.run_until_complete(site.start())
            self.port = site._server.sockets[0].getsockname()[1]
            self._started.set()
            loop.run_until_complete(self._stop)
            loop.run_until_complete(runner.cleanup())
            loop.close()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        assert self._started.wait(10)
        return self

    def __exit__(self, *exc):
        import asyncio

        loop = self._stop.get_loop()
        loop.call_soon_threadsafe(self._stop.set_result, None)
        self._thread.join(10)


def _range_body(request: web.Request, data: bytes):
    """Shared Range semantics for the emulators."""
    rng = request.headers.get("Range")
    if rng and rng.startswith("bytes="):
        start = int(rng[len("bytes="):].rstrip("-").split("-")[0])
        return web.Response(
            status=206,
            body=data[start:],
            headers={
                "Content-Range": f"bytes {start}-{len(data)-1}/{len(data)}",
                "ETag": '"%s"' % hashlib.md5(data).hexdigest(),
            },
        )
    return web.Response(
        body=data, headers={"ETag": '"%s"' % hashlib.md5(data).hexdigest()}
    )


# --------------------------------------------------------------------------- #
# plain http(s): download + mid-stream failure resume
# --------------------------------------------------------------------------- #


def test_http_fetch_simple(tmp_path):
    data = b"w" * 300_000

    async def get(request):
        return _range_body(request, data)

    app = web.Application()
    app.router.add_get("/models/m.bin", get)
    with _Server(app) as srv:
        dest = storage.download(
            f"http://127.0.0.1:{srv.port}/models/m.bin", str(tmp_path / "mnt")
        )
    assert open(dest, "rb").read() == data
    assert storage.verify(dest)


def test_http_resume_after_midstream_drop(tmp_path):
    """First attempt dies after ~64KiB; the fetcher must RESUME with a Range
    header (not restart), and the bytes must verify."""
    data = bytes(range(256)) * 1024  # 256 KiB, position-dependent content
    state = {"calls": 0, "ranges": []}

    async def get(request):
        state["calls"] += 1
        state["ranges"].append(request.headers.get("Range"))
        if state["calls"] == 1:
            resp = web.StreamResponse(
                status=200,
                headers={
                    "Content-Length": str(len(data)),
                    "ETag": '"stable-etag"',
                },
            )
            await resp.prepare(request)
            await resp.write(data[:65536])
            # kill the TCP stream mid-body → client sees a short read
            request.transport.close()
            return resp
        return _range_body(request, data)

    app = web.Application()
    app.router.add_get("/w.bin", get)
    with _Server(app) as srv:
        dest = storage.download(
            f"http://127.0.0.1:{srv.port}/w.bin", str(tmp_path / "mnt")
        )
    assert open(dest, "rb").read() == data
    assert state["calls"] >= 2
    resumed = [r for r in state["ranges"] if r]
    assert resumed and resumed[0].startswith("bytes=")
    # resume started from a non-zero offset — it did not refetch byte 0
    assert int(resumed[0][len("bytes="):].rstrip("-")) > 0


def test_http_404_is_permanent_no_retry(tmp_path):
    state = {"calls": 0}

    async def get(request):
        state["calls"] += 1
        raise web.HTTPNotFound()

    app = web.Application()
    app.router.add_get("/gone.bin", get)
    with _Server(app) as srv:
        with pytest.raises(FileNotFoundError):
            storage.download(
                f"http://127.0.0.1:{srv.port}/gone.bin",
                str(tmp_path / "mnt"),
                retries=3,
            )
    assert state["calls"] == 1  # permanent: storage.download must not retry


# --------------------------------------------------------------------------- #
# S3 emulator: ListObjectsV2 + GET, SigV4 checked server-side
# --------------------------------------------------------------------------- #


def _s3_app(objects: dict[str, bytes], seen: dict):
    """Bucket 'models' speaking the two S3 REST calls the fetcher makes."""

    async def bucket(request: web.Request):
        seen.setdefault("auth", []).append(
            request.headers.get("Authorization")
        )
        q = request.query
        assert q.get("list-type") == "2"
        prefix = q.get("prefix", "")
        keys = sorted(k for k in objects if k.startswith(prefix))
        page, token = keys[:2], None  # force pagination at >2 keys
        rest = keys[2:]
        if q.get("continuation-token"):
            page = rest
        elif rest:
            token = "next-page"
        items = "".join(
            f"<Contents><Key>{escape(k)}</Key>"
            f"<Size>{len(objects[k])}</Size></Contents>"
            for k in page
        )
        trunc = "true" if token else "false"
        tok = f"<NextContinuationToken>{token}</NextContinuationToken>" if token else ""
        xml = (
            '<?xml version="1.0"?>'
            '<ListBucketResult xmlns='
            '"http://s3.amazonaws.com/doc/2006-03-01/">'
            f"<IsTruncated>{trunc}</IsTruncated>{tok}{items}"
            "</ListBucketResult>"
        )
        return web.Response(text=xml, content_type="application/xml")

    async def obj(request: web.Request):
        seen.setdefault("auth", []).append(request.headers.get("Authorization"))
        key = urllib.parse.unquote(request.match_info["key"])
        if key not in objects:
            raise web.HTTPNotFound()
        return _range_body(request, objects[key])

    app = web.Application()
    app.router.add_get("/models", bucket)
    app.router.add_get("/models/{key:.+}", obj)
    return app


def test_s3_prefix_download_with_pagination(tmp_path, monkeypatch):
    objects = {
        "bert/config.json": b'{"hidden": 768}',
        "bert/weights.bin": b"W" * 100_000,
        "bert/vocab/tokens.txt": b"a\nb\nc\n",
        "other/skip.bin": b"no",
    }
    seen: dict = {}
    with _Server(_s3_app(objects, seen)) as srv:
        monkeypatch.setenv("AWS_ENDPOINT_URL", f"http://127.0.0.1:{srv.port}")
        monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
        dest = storage.download("s3://models/bert", str(tmp_path / "mnt"))
    import os

    assert sorted(
        os.path.relpath(os.path.join(r, f), dest)
        for r, _, fs in os.walk(dest)
        for f in fs
    ) == ["config.json", "vocab/tokens.txt", "weights.bin"]
    assert open(os.path.join(dest, "weights.bin"), "rb").read() == objects[
        "bert/weights.bin"
    ]
    assert storage.verify(dest, uri="s3://models/bert")
    # anonymous: no Authorization header was sent
    assert not any(seen["auth"])


def test_s3_single_key_and_sigv4(tmp_path, monkeypatch):
    objects = {"bert/weights.bin": b"signed-bytes" * 1000}
    seen: dict = {}
    with _Server(_s3_app(objects, seen)) as srv:
        monkeypatch.setenv("AWS_ENDPOINT_URL", f"http://127.0.0.1:{srv.port}")
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKIDEXAMPLE")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "secretkey")
        monkeypatch.setenv("AWS_REGION", "us-west-2")
        dest = storage.download(
            "s3://models/bert/weights.bin", str(tmp_path / "mnt")
        )
    assert open(dest, "rb").read() == objects["bert/weights.bin"]
    auths = [a for a in seen["auth"] if a]
    assert auths, "SigV4 Authorization header missing"
    for a in auths:
        assert a.startswith("AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/")
        assert "/us-west-2/s3/aws4_request" in a
        assert "SignedHeaders=" in a and "Signature=" in a
        signed = a.split("SignedHeaders=")[1].split(",")[0].split(";")
        assert "host" in signed and "x-amz-date" in signed


def test_s3_missing_prefix_is_permanent(tmp_path, monkeypatch):
    seen: dict = {}
    with _Server(_s3_app({}, seen)) as srv:
        monkeypatch.setenv("AWS_ENDPOINT_URL", f"http://127.0.0.1:{srv.port}")
        monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
        with pytest.raises(FileNotFoundError, match="no such key"):
            storage.download("s3://models/nope", str(tmp_path / "mnt"))


# --------------------------------------------------------------------------- #
# GCS emulator: JSON list + alt=media via STORAGE_EMULATOR_HOST
# --------------------------------------------------------------------------- #


def _gcs_app(objects: dict[str, bytes], seen: dict):
    async def list_objects(request: web.Request):
        seen.setdefault("auth", []).append(request.headers.get("Authorization"))
        prefix = request.query.get("prefix", "")
        names = sorted(n for n in objects if n.startswith(prefix))
        page = request.query.get("pageToken")
        items, body = (names[1:] if page else names[:1]), {}
        if not page and len(names) > 1:
            body["nextPageToken"] = "page2"
        body["items"] = [{"name": n, "size": str(len(objects[n]))} for n in items]
        return web.json_response(body)

    async def get_object(request: web.Request):
        seen.setdefault("auth", []).append(request.headers.get("Authorization"))
        name = urllib.parse.unquote(request.match_info["name"])
        if request.query.get("alt") != "media" or name not in objects:
            raise web.HTTPNotFound()
        return _range_body(request, objects[name])

    app = web.Application()
    app.router.add_get("/storage/v1/b/{bucket}/o", list_objects)
    app.router.add_get("/storage/v1/b/{bucket}/o/{name:.+}", get_object)
    return app


def test_gs_prefix_download_with_token(tmp_path, monkeypatch):
    objects = {
        "resnet/saved.orbax": b"O" * 50_000,
        "resnet/meta.json": b"{}",
    }
    seen: dict = {}
    with _Server(_gcs_app(objects, seen)) as srv:
        monkeypatch.setenv("STORAGE_EMULATOR_HOST", f"127.0.0.1:{srv.port}")
        monkeypatch.setenv("GOOGLE_OAUTH_ACCESS_TOKEN", "tok-123")
        dest = storage.download("gs://zoo/resnet", str(tmp_path / "mnt"))
    import os

    assert sorted(os.listdir(dest)) == ["meta.json", "saved.orbax"]
    assert open(os.path.join(dest, "saved.orbax"), "rb").read() == objects[
        "resnet/saved.orbax"
    ]
    # bearer token flowed on list AND media requests
    assert all(a == "Bearer tok-123" for a in seen["auth"])


def test_gs_single_object_cache_reuse(tmp_path, monkeypatch):
    objects = {"m/w.bin": b"gw" * 10_000}
    seen: dict = {}
    with _Server(_gcs_app(objects, seen)) as srv:
        monkeypatch.setenv("STORAGE_EMULATOR_HOST", f"127.0.0.1:{srv.port}")
        monkeypatch.delenv("GOOGLE_OAUTH_ACCESS_TOKEN", raising=False)
        d1 = storage.download("gs://zoo/m/w.bin", str(tmp_path / "mnt"))
        n_after_first = len(seen["auth"])
        d2 = storage.download("gs://zoo/m/w.bin", str(tmp_path / "mnt"))
    assert d1 == d2
    assert open(d1, "rb").read() == objects["m/w.bin"]
    # second download() hit the verified cache: zero additional requests
    assert len(seen["auth"]) == n_after_first


# --------------------------------------------------------------------------- #
# SigV4 canonicalization details
# --------------------------------------------------------------------------- #


def test_sigv4_signature_is_deterministic_and_header_complete(monkeypatch):
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKID")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "sk")
    monkeypatch.setenv("AWS_SESSION_TOKEN", "sess")
    sign = cloudstorage._sigv4_signer("eu-central-1")
    h: dict[str, str] = {}
    sign("GET", "http://s3.local/models?list-type=2&prefix=a%2Fb", h)
    assert h["x-amz-content-sha256"] == "UNSIGNED-PAYLOAD"
    assert h["x-amz-security-token"] == "sess"
    assert h["Host"] == "s3.local"
    auth = h["Authorization"]
    assert "/eu-central-1/s3/aws4_request" in auth
    signed = auth.split("SignedHeaders=")[1].split(",")[0].split(";")
    # every header present at signing time is signed, sorted
    assert signed == sorted(k.lower() for k in h if k != "Authorization")


def test_anonymous_when_no_creds(monkeypatch):
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
    assert cloudstorage._sigv4_signer("us-east-1") is None


def test_chunked_midbody_drop_resumes_not_restarts(tmp_path):
    """No Content-Length (chunked) + mid-chunk connection kill →
    http.client.IncompleteRead. That must feed the RESUME loop inside
    http_get_to_file, not escape to storage.download's fresh-staging
    retry (which would refetch from byte 0) or abort the download."""
    import asyncio as aio

    data = bytes(range(256)) * 2048  # 512 KiB
    state = {"calls": 0, "ranges": []}

    async def get(request):
        state["calls"] += 1
        state["ranges"].append(request.headers.get("Range"))
        if state["calls"] == 1:
            resp = web.StreamResponse(status=200)  # no Content-Length
            resp.enable_chunked_encoding()
            await resp.prepare(request)
            await resp.write(data[:262_144])
            await aio.sleep(0.2)  # let the partial chunk actually flush
            request.transport.close()  # kill mid-chunk
            return resp
        return _range_body(request, data)

    app = web.Application()
    app.router.add_get("/c.bin", get)
    with _Server(app) as srv:
        dest = storage.download(
            f"http://127.0.0.1:{srv.port}/c.bin", str(tmp_path / "mnt")
        )
    assert open(dest, "rb").read() == data
    resumed = [r for r in state["ranges"] if r]
    assert resumed, "second attempt did not carry a Range header (restarted)"
    assert int(resumed[0][len("bytes="):].rstrip("-")) > 0


def test_sigv4_key_with_space_single_encoding(tmp_path, monkeypatch):
    """Keys needing percent-encoding must be signed over the SINGLE-encoded
    path; the emulator sees /models/my%20model.bin and byte-compares."""
    objects = {"zoo/my model.bin": b"spacey" * 500}
    seen: dict = {}
    with _Server(_s3_app(objects, seen)) as srv:
        monkeypatch.setenv("AWS_ENDPOINT_URL", f"http://127.0.0.1:{srv.port}")
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKID")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "sk")
        monkeypatch.setenv("AWS_REGION", "us-east-1")
        dest = storage.download(
            "s3://models/zoo/my model.bin", str(tmp_path / "mnt")
        )
    assert open(dest, "rb").read() == objects["zoo/my model.bin"]
    assert all(a and "Signature=" in a for a in seen["auth"])


def test_s3_prefix_does_not_leak_sibling_keys(tmp_path, monkeypatch):
    """'bert-old/...' string-prefix-matches 'bert' in the listing but is NOT
    under 'bert/' — it must be excluded, never basename-flattened in."""
    objects = {
        "bert/weights.bin": b"GOOD" * 1000,
        "bert-old/weights.bin": b"STALE" * 1000,
        "bert/config.json": b"{}",
    }
    seen: dict = {}
    with _Server(_s3_app(objects, seen)) as srv:
        monkeypatch.setenv("AWS_ENDPOINT_URL", f"http://127.0.0.1:{srv.port}")
        monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
        dest = storage.download("s3://models/bert", str(tmp_path / "mnt"))
    import os

    assert sorted(
        os.path.relpath(os.path.join(r, f), dest)
        for r, _, fs in os.walk(dest)
        for f in fs
    ) == ["config.json", "weights.bin"]
    assert open(os.path.join(dest, "weights.bin"), "rb").read() == objects[
        "bert/weights.bin"
    ]


def test_resume_at_eof_416_completes(tmp_path):
    """Chunked body fully delivered but connection died before the terminal
    chunk: the resume offset == file size, a real server answers 416, and
    the download must COMPLETE (the bytes are all here), not abort."""
    data = b"Z" * 200_000
    state = {"calls": 0}

    async def get(request):
        state["calls"] += 1
        rng = request.headers.get("Range")
        if rng:
            start = int(rng[len("bytes="):].rstrip("-").split("-")[0])
            if start >= len(data):
                raise web.HTTPRequestRangeNotSatisfiable(
                    headers={"Content-Range": f"bytes */{len(data)}"}
                )
            return _range_body(request, data)
        resp = web.StreamResponse(status=200)  # chunked, no Content-Length
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        await resp.write(data)
        import asyncio as aio

        await aio.sleep(0.2)
        request.transport.close()  # die before the terminal chunk
        return resp

    app = web.Application()
    app.router.add_get("/z.bin", get)
    with _Server(app) as srv:
        dest = storage.download(
            f"http://127.0.0.1:{srv.port}/z.bin", str(tmp_path / "mnt")
        )
    assert open(dest, "rb").read() == data
    assert state["calls"] >= 2  # the 416 resume round-trip happened


# --------------------------------------------------------------------- hdfs


def _webhdfs_app(tree: dict[str, bytes], seen: dict):
    """A NameNode speaking the three WebHDFS ops the fetcher uses, with
    OPEN answering via a 307 redirect to a 'datanode' route (the real
    protocol shape)."""

    def classify(path):
        path = "/" + path.strip("/")
        if path.strip("/") in {k.rsplit("/", 1)[0] for k in tree} or any(
            k.startswith(path.strip("/") + "/") for k in tree
        ):
            return "DIRECTORY"
        if path.strip("/") in tree:
            return "FILE"
        return None

    async def api(request: web.Request):
        path = request.match_info["path"]
        op = request.query.get("op")
        seen.setdefault("ops", []).append((op, "/" + path))
        seen.setdefault("users", []).append(request.query.get("user.name"))
        kind = classify(path)
        if op == "GETFILESTATUS":
            if kind is None:
                raise web.HTTPNotFound()
            return web.json_response({"FileStatus": {
                "type": kind, "pathSuffix": "", "length": 0}})
        if op == "LISTSTATUS":
            base = path.strip("/")
            names = {}
            for k in tree:
                if not k.startswith(base + "/"):
                    continue
                head = k[len(base) + 1:].split("/", 1)[0]
                names[head] = (
                    "DIRECTORY" if "/" in k[len(base) + 1:] else "FILE"
                )
            return web.json_response({"FileStatuses": {"FileStatus": [
                {"pathSuffix": n, "type": t, "length": 0}
                for n, t in sorted(names.items())
            ]}})
        if op == "OPEN":
            if kind != "FILE":
                raise web.HTTPNotFound()
            raise web.HTTPTemporaryRedirect(f"/datanode/{path}")
        raise web.HTTPBadRequest()

    async def datanode(request: web.Request):
        key = request.match_info["path"].strip("/")
        seen.setdefault("datanode", []).append(key)
        return _range_body(request, tree[key])

    app = web.Application()
    app.router.add_get("/webhdfs/v1/{path:.+}", api)
    app.router.add_get("/datanode/{path:.+}", datanode)
    return app


def test_hdfs_directory_download(tmp_path, monkeypatch):
    tree = {
        "models/bert/config.json": b'{"hidden": 768}',
        "models/bert/weights.bin": b"H" * 50_000,
        "models/bert/vocab/tokens.txt": b"a\nb\n",
        "models/other/skip.bin": b"no",
    }
    seen: dict = {}
    with _Server(_webhdfs_app(tree, seen)) as srv:
        monkeypatch.setenv("WEBHDFS_ENDPOINT", f"http://127.0.0.1:{srv.port}")
        monkeypatch.setenv("HADOOP_USER_NAME", "kft")
        dest = storage.download(
            "hdfs://namenode/models/bert", str(tmp_path / "mnt")
        )
    import os

    got = sorted(
        os.path.relpath(os.path.join(r, f), dest)
        for r, _, fs in os.walk(dest)
        for f in fs
    )
    assert got == ["config.json", "vocab/tokens.txt", "weights.bin"]
    assert open(os.path.join(dest, "weights.bin"), "rb").read() == b"H" * 50_000
    assert storage.verify(dest, uri="hdfs://namenode/models/bert")
    # bytes came through the DataNode redirect; identity rode user.name
    assert seen["datanode"]
    assert all(u == "kft" for u in seen["users"])


def test_hdfs_single_file_and_missing(tmp_path, monkeypatch):
    tree = {"models/one.bin": b"single" * 100}
    with _Server(_webhdfs_app(tree, {})) as srv:
        monkeypatch.setenv("WEBHDFS_ENDPOINT", f"http://127.0.0.1:{srv.port}")
        dest = storage.download(
            "hdfs://nn:9870/models/one.bin", str(tmp_path / "mnt")
        )
        assert open(dest, "rb").read() == b"single" * 100
        with pytest.raises(FileNotFoundError, match="no such file"):
            storage.download(
                "hdfs://nn/models/nope.bin", str(tmp_path / "mnt2")
            )


def _hostile_webhdfs_app(suffix: str):
    """A compromised NameNode returning a traversal-shaped pathSuffix in
    LISTSTATUS (ADVICE r5: the listing is untrusted remote input)."""
    from aiohttp import web

    async def api(request: web.Request):
        op = request.query.get("op")
        if op == "GETFILESTATUS":
            return web.json_response({"FileStatus": {
                "type": "DIRECTORY", "pathSuffix": "", "length": 0}})
        if op == "LISTSTATUS":
            return web.json_response({"FileStatuses": {"FileStatus": [
                {"pathSuffix": suffix, "type": "FILE", "length": 0},
            ]}})
        return _range_body(request, b"evil-bytes")

    app = web.Application()
    app.router.add_get("/webhdfs/v1{path:.*}", api)
    return app


@pytest.mark.parametrize("suffix", ["../escape.bin", "..", "a/b.bin", "x\\y"])
def test_hdfs_rejects_traversal_path_suffix(tmp_path, monkeypatch, suffix):
    """pathSuffix values containing separators or dot-dots must fail the
    fetch closed — never write outside the staging root."""
    import os

    with _Server(_hostile_webhdfs_app(suffix)) as srv:
        monkeypatch.setenv("WEBHDFS_ENDPOINT", f"http://127.0.0.1:{srv.port}")
        with pytest.raises(FileNotFoundError, match="pathSuffix"):
            storage.download(
                "hdfs://namenode/models/m", str(tmp_path / "mnt"),
                retries=1,
            )
    # nothing escaped: the parent of the staging dir holds only our dirs
    outside = [
        p for p in os.listdir(tmp_path)
        if p not in ("mnt",) and not p.startswith(".")
    ]
    assert outside == []
    assert not os.path.exists(tmp_path.parent / "escape.bin")
