"""kft lint: engine mechanics + one firing/silent fixture pair per pass.

Layout mirrors the acceptance contract: every pass must (a) fire on a
fixture that violates its rule, (b) stay silent on the fixed version,
(c) respect ``# kft: noqa[rule]``, and (d) respect the baseline pin.
The last test asserts the repo itself is clean modulo the checked-in
baseline — the CI gate in test form.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import pytest

from kubeflow_tpu.analysis.engine import (
    LintConfig,
    load_config,
    run_lint,
    write_baseline,
)
from kubeflow_tpu.cli import main as cli_main

REPO_ROOT = Path(__file__).resolve().parent.parent


def make_repo(tmp_path: Path, files: dict[str, str]) -> Path:
    """A throwaway repo: {relative path: source}."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return tmp_path


def lint(tmp_path: Path, files: dict[str, str], **kw):
    make_repo(tmp_path, files)
    config = LintConfig(root=str(tmp_path), baseline=None)
    return run_lint(config, **kw)


def rules_of(result) -> set[str]:
    return {f.rule for f in result.findings}


# -- lock-discipline ------------------------------------------------------ #

LOCKED_CLASS = """\
import threading

class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {{}}

    def add(self, k, v):
        with self._lock:
            self._items[k] = v

    def drop(self, k):
        {drop_body}
"""


def test_lock_discipline_fires_on_bare_mutation(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/mod.py": LOCKED_CLASS.format(
            drop_body="self._items.pop(k, None)"
        ),
    })
    assert rules_of(res) == {"lock-discipline"}
    (f,) = res.findings
    assert "_items" in f.message and "Ledger.drop" in f.message


def test_lock_discipline_silent_when_locked(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/mod.py": LOCKED_CLASS.format(
            drop_body="with self._lock:\n            self._items.pop(k, None)"
        ),
    })
    assert res.findings == []


def test_lock_discipline_locked_suffix_methods_exempt(tmp_path):
    src = (
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._held = {}\n"
        "    def admit(self, k):\n"
        "        with self._lock:\n"
        "            self._admit_locked(k)\n"
        "    def _admit_locked(self, k):\n"
        "        self._held[k] = 1\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    # _held is mutated under the lock only via the *_locked convention;
    # make it 'guarded' via an explicit locked mutation too
    src += (
        "    def reset(self):\n"
        "        with self._lock:\n"
        "            self._held.clear()\n"
    )
    res = lint(tmp_path, {"kubeflow_tpu/mod.py": src})
    assert res.findings == []


def test_lock_discipline_thread_entry_read(tmp_path):
    src = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._work = []\n"
        "        threading.Thread(target=self._run, daemon=True).start()\n"
        "    def put(self, x):\n"
        "        with self._lock:\n"
        "            self._work.append(x)\n"
        "    def _run(self):\n"
        "        for item in self._work:\n"
        "            print(item)\n"
    )
    res = lint(tmp_path, {"kubeflow_tpu/mod.py": src})
    assert any(
        f.rule == "lock-discipline" and "thread entry point reads" in f.message
        for f in res.findings
    )


# -- metric-registry ------------------------------------------------------ #

NAMES_PY = (
    '"""names."""\n'
    'JOBS_TOTAL = "kft_jobs_total"\n'
    'WAIT_SECONDS = "kft_wait_seconds"\n'
)


def test_metric_registry_flags_bare_literal_and_typo(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/obs/names.py": NAMES_PY,
        "kubeflow_tpu/mod.py": (
            "from kubeflow_tpu.obs import names, prom\n"
            'C = prom.REGISTRY.counter("kft_jobs_total", "h")\n'
            'oops = "kft_jobs_totle"\n'
            "W = prom.REGISTRY.histogram(names.WAIT_SECONDS, 'h')\n"
        ),
    })
    msgs = [f.message for f in res.findings]
    assert any('"kft_jobs_total"' in m and "bare metric-name" in m for m in msgs)
    assert any("kft_jobs_totle" in m and "no obs/names.py constant" in m for m in msgs)


def test_metric_registry_silent_on_constants(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/obs/names.py": NAMES_PY,
        "kubeflow_tpu/mod.py": (
            "from kubeflow_tpu.obs import names, prom\n"
            'C = prom.REGISTRY.counter(names.JOBS_TOTAL, "h")\n'
            "W = prom.REGISTRY.histogram(names.WAIT_SECONDS, 'h')\n"
        ),
    })
    assert res.findings == []


def test_metric_registry_kind_and_label_drift(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/obs/names.py": NAMES_PY,
        "kubeflow_tpu/a.py": (
            "from kubeflow_tpu.obs import names, prom\n"
            'A = prom.REGISTRY.counter(names.JOBS_TOTAL, "h", labels=("queue",))\n'
            "W = prom.REGISTRY.histogram(names.WAIT_SECONDS, 'h')\n"
        ),
        "kubeflow_tpu/b.py": (
            "from kubeflow_tpu.obs import names, prom\n"
            'B = prom.REGISTRY.gauge(names.JOBS_TOTAL, "h", labels=("tenant",))\n'
        ),
    })
    msgs = " | ".join(f.message for f in res.findings)
    assert "registered as gauge here but as counter" in msgs
    assert "label set" in msgs and "drifts" in msgs


def test_metric_registry_fstring_prefix_and_dead_name(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/obs/names.py": NAMES_PY,
        "kubeflow_tpu/mod.py": (
            "from kubeflow_tpu.obs import names, prom\n"
            "C = prom.REGISTRY.counter(names.JOBS_TOTAL, 'h')\n"
            "def expo(k, v):\n"
            "    return f'kft_engine_{k} {v}'\n"
        ),
    })
    msgs = [f.message for f in res.findings]
    assert any('"kft_engine_"' in m for m in msgs)  # f-string prefix literal
    dead = [f for f in res.findings if "never referenced" in f.message]
    assert [f.severity for f in dead] == ["warning"]
    assert "WAIT_SECONDS" in dead[0].message


# -- jax-sync ------------------------------------------------------------- #

HOT_LOOP_BAD = (
    "import jax\n"
    "import numpy as np\n"
    "def step(fn, state, batch, metrics):\n"
    "    out = fn(state, batch)\n"
    "    jax.block_until_ready(out)\n"
    "    loss = metrics['loss'].item()\n"
    "    arr = np.asarray(out)\n"
    "    jitted = jax.jit(fn, donate_argnums=(0,))\n"
    "    return out, loss, arr, jitted\n"
)


def test_jax_sync_fires_in_scoped_file(tmp_path):
    res = lint(tmp_path, {"kubeflow_tpu/train/loop.py": HOT_LOOP_BAD})
    msgs = " | ".join(f.message for f in res.findings)
    assert len([f for f in res.findings if f.rule == "jax-sync"]) == 4
    for needle in ("block_until_ready", ".item()", "np.asarray", "donate_argnums"):
        assert needle in msgs


def test_jax_sync_silent_outside_scope(tmp_path):
    res = lint(tmp_path, {"kubeflow_tpu/models/thing.py": HOT_LOOP_BAD})
    assert [f for f in res.findings if f.rule == "jax-sync"] == []


def test_jax_sync_silent_on_clean_loop(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/train/loop.py": (
            "import jax\n"
            "def step(fn, state, batch):\n"
            "    return jax.jit(fn)(state, batch)\n"
        ),
    })
    assert res.findings == []


# -- thread-join ----------------------------------------------------------- #


def test_thread_join_fires_on_unjoined_nondaemon(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/mod.py": (
            "import threading\n"
            "class Loop:\n"
            "    def start(self):\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "        self._t.start()\n"
            "    def _run(self):\n"
            "        pass\n"
        ),
    })
    assert rules_of(res) == {"thread-join"}


def test_thread_join_silent_with_daemon_or_join(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/a.py": (
            "import threading\n"
            "t = threading.Thread(target=print, daemon=True)\n"
        ),
        "kubeflow_tpu/b.py": (
            "import threading\n"
            "class Loop:\n"
            "    def start(self):\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "        self._t.start()\n"
            "    def _run(self):\n"
            "        pass\n"
            "    def stop(self):\n"
            "        self._t.join()\n"
        ),
    })
    assert res.findings == []


# -- monotonic-clock -------------------------------------------------------- #


def test_monotonic_clock_fires_in_scoped_file_only(tmp_path):
    src = (
        "import time\n"
        "def age(since):\n"
        "    return time.time() - since\n"
    )
    res = lint(tmp_path, {
        "kubeflow_tpu/obs/heartbeat.py": src,
        "kubeflow_tpu/pipelines/runner.py": src,  # unscoped: allowed
    })
    assert [f.path for f in res.findings] == ["kubeflow_tpu/obs/heartbeat.py"]
    assert rules_of(res) == {"monotonic-clock"}


def test_monotonic_clock_silent_on_monotonic(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/obs/heartbeat.py": (
            "import time\n"
            "def age(since):\n"
            "    return time.monotonic() - since\n"
        ),
    })
    assert res.findings == []


# -- unseeded-random -------------------------------------------------------- #


def test_unseeded_random_fires_in_chaos(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/chaos/mod.py": (
            "import random\n"
            "import numpy as np\n"
            "def pick(items):\n"
            "    rng = random.Random()\n"
            "    jitter = random.random()\n"
            "    noise = np.random.rand()\n"
            "    return rng, jitter, noise, random.choice(items)\n"
        ),
    })
    assert len([f for f in res.findings if f.rule == "unseeded-random"]) == 4


def test_unseeded_random_silent_on_seeded_and_out_of_scope(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/chaos/mod.py": (
            "import random\n"
            "import numpy as np\n"
            "def pick(seed):\n"
            "    return random.Random(seed), np.random.default_rng(seed)\n"
        ),
        "kubeflow_tpu/models/init.py": (
            "import random\n"
            "x = random.random()\n"  # out of scope: allowed
        ),
    })
    assert res.findings == []


# -- suppressions + baseline ------------------------------------------------ #


def test_noqa_suppresses_named_rule_only(tmp_path):
    res = lint(tmp_path, {
        "kubeflow_tpu/chaos/a.py": (
            "import random\n"
            "x = random.random()  # kft: noqa[unseeded-random] — fixture\n"
            "y = random.random()  # kft: noqa[lock-discipline] — wrong rule\n"
            "z = random.random()  # kft: noqa — blanket\n"
        ),
    })
    assert len(res.findings) == 1
    assert res.findings[0].line == 3
    assert res.noqa_suppressed == 2


def test_baseline_pins_legacy_but_fails_new(tmp_path):
    files = {
        "kubeflow_tpu/chaos/a.py": "import random\nx = random.random()\n",
    }
    make_repo(tmp_path, files)
    config = LintConfig(root=str(tmp_path), baseline="lint_baseline.json")
    first = run_lint(config, baseline=False)
    assert len(first.findings) == 1
    write_baseline(first.findings, str(tmp_path / "lint_baseline.json"))

    pinned = run_lint(config)
    assert pinned.findings == [] and pinned.baseline_matched == 1

    # a NEW violation is not absorbed by the old pin
    (tmp_path / "kubeflow_tpu/chaos/a.py").write_text(
        "import random\nx = random.random()\ny = random.choice([1])\n"
    )
    again = run_lint(config)
    assert len(again.findings) == 1
    assert "random.choice" in again.findings[0].message
    assert again.baseline_matched == 1

    # fixing the pinned finding leaves a stale baseline entry to prune
    (tmp_path / "kubeflow_tpu/chaos/a.py").write_text("x = 1\n")
    clean = run_lint(config)
    assert clean.findings == [] and len(clean.stale_baseline) == 1


# -- config + CLI ------------------------------------------------------------ #


def test_pyproject_config_parsing(tmp_path):
    make_repo(tmp_path, {
        "pyproject.toml": (
            "[project]\n"
            'name = "x"\n'
            "[tool.kft-lint]\n"
            'include = ["kubeflow_tpu"]\n'
            "rules = [  # multi-line arrays must survive the 3.10 fallback\n"
            '    "unseeded-random",\n'
            '    "thread-join",\n'
            "]\n"
            'baseline = "pins.json"\n'
            "[tool.kft-lint.scopes]\n"
            'unseeded-random = ["kubeflow_tpu/randomzone"]\n'
        ),
    })
    cfg = load_config(str(tmp_path))
    assert cfg.rules == ("unseeded-random", "thread-join")
    assert cfg.baseline == "pins.json"
    assert cfg.scopes["unseeded-random"] == ("kubeflow_tpu/randomzone",)
    # default scopes for other rules survive the override
    assert "jax-sync" in cfg.scopes


def test_metric_registry_partial_path_run_still_resolves_names(tmp_path):
    """`kft lint some/subdir` must not flag constants as unknown just
    because names.py fell outside the narrowed discovery — and must not
    emit dead-name warnings from a partial usage scan."""
    make_repo(tmp_path, {
        "kubeflow_tpu/obs/names.py": NAMES_PY,
        "kubeflow_tpu/serve/mod.py": (
            "from kubeflow_tpu.obs import names, prom\n"
            'C = prom.REGISTRY.counter(names.JOBS_TOTAL, "h")\n'
        ),
    })
    config = LintConfig(root=str(tmp_path), baseline=None)
    res = run_lint(config, paths=["kubeflow_tpu/serve"])
    assert res.findings == []


def test_repo_pyproject_table_roundtrip():
    """The real [tool.kft-lint] table parses identically whether tomllib
    exists (3.11+) or the fallback runs (this image's 3.10)."""
    cfg = load_config(str(REPO_ROOT))
    assert cfg.rules is not None and "lock-discipline" in cfg.rules
    assert cfg.baseline == "lint_baseline.json"
    assert cfg.include == ("kubeflow_tpu",)


def test_cli_exit_codes_and_json(tmp_path, capsys):
    make_repo(tmp_path, {
        "kubeflow_tpu/chaos/a.py": "import random\nx = random.random()\n",
    })
    root = str(tmp_path)
    assert cli_main(["lint", "--root", root]) == 1
    out = capsys.readouterr().out
    assert "unseeded-random" in out

    assert cli_main(["lint", "--root", root, "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1 and doc["files"] == 1
    (finding,) = doc["findings"]
    assert set(finding) == {"rule", "path", "line", "severity", "message"}
    assert finding["rule"] == "unseeded-random"

    # rule filter: a rule that doesn't fire here → clean exit
    assert cli_main(["lint", "--root", root, "--rule", "jax-sync"]) == 0
    capsys.readouterr()
    # usage error: unknown rule
    assert cli_main(["lint", "--root", root, "--rule", "bogus"]) == 2
    assert "unknown rule" in capsys.readouterr().err

    # pin, then strict is clean
    assert cli_main(["lint", "--root", root, "--update-baseline"]) == 0
    capsys.readouterr()
    assert cli_main(["lint", "--root", root, "--strict"]) == 0
    capsys.readouterr()
    # --no-baseline resurfaces the pinned finding
    assert cli_main(["lint", "--root", root, "--no-baseline"]) == 1
    capsys.readouterr()


def test_cli_rejects_unparseable_file(tmp_path, capsys):
    make_repo(tmp_path, {"kubeflow_tpu/bad.py": "def broken(:\n"})
    assert cli_main(["lint", "--root", str(tmp_path)]) == 2
    assert "cannot parse" in capsys.readouterr().err


# -- the repo itself --------------------------------------------------------- #


def test_repo_is_clean_modulo_baseline():
    """The CI gate in test form: `kft lint --strict` semantics over the
    real tree — zero unpinned findings, and the checked-in baseline holds
    at most 10 pinned legacy findings with no stale entries."""
    config = load_config(str(REPO_ROOT))
    result = run_lint(config)
    assert result.findings == [], [f.render() for f in result.findings]
    assert result.stale_baseline == []
    assert result.baseline_matched <= 10
    assert result.parse_errors == []


def test_repo_baseline_file_is_small():
    doc = json.loads((REPO_ROOT / "lint_baseline.json").read_text())
    assert len(doc["findings"]) <= 10
