"""AutoML plane tests (SURVEY.md §4: Katib suggestion-service pytest analog —
fixed search spaces, gRPC stubs, controller semantics)."""

import math

import pytest

from kubeflow_tpu.tune.controller import (
    CallableTrialRunner,
    ExperimentController,
    tune,
)
from kubeflow_tpu.tune.earlystop import MedianStop
from kubeflow_tpu.tune import metrics as tmetrics
from kubeflow_tpu.tune.spec import (
    AlgorithmSpec,
    EarlyStoppingSpec,
    ExperimentSpec,
    Objective,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    Trial,
    TrialAssignment,
    TrialState,
    substitute_template,
)
from kubeflow_tpu.tune.suggest import make_suggester


def _space():
    return (
        ParameterSpec("lr", ParameterType.DOUBLE, min=1e-4, max=1e-1, log_scale=True),
        ParameterSpec("layers", ParameterType.INT, min=1, max=8),
        ParameterSpec("opt", ParameterType.CATEGORICAL, values=("sgd", "adam")),
    )


def _exp(algorithm="random", goal=None, max_trials=12, parallel=3, **alg_settings):
    return ExperimentSpec(
        name="e",
        parameters=_space(),
        objective=Objective("loss", ObjectiveType.MINIMIZE, goal=goal),
        algorithm=AlgorithmSpec(algorithm, alg_settings),
        parallel_trial_count=parallel,
        max_trial_count=max_trials,
    )


# ----------------------------------------------------------------- parameters


def test_parameter_mappings_and_validation():
    lr = ParameterSpec("lr", ParameterType.DOUBLE, min=1e-4, max=1e-1, log_scale=True)
    assert lr.from_unit(0.0) == pytest.approx(1e-4)
    assert lr.from_unit(1.0) == pytest.approx(1e-1)
    assert lr.to_unit(1e-2) == pytest.approx(lr.to_unit(1e-2))
    mid = lr.from_unit(0.5)
    assert mid == pytest.approx(math.sqrt(1e-4 * 1e-1))  # log-space midpoint

    it = ParameterSpec("n", ParameterType.INT, min=1, max=8)
    assert it.from_unit(0.999) == 8 and isinstance(it.from_unit(0.3), int)

    cat = ParameterSpec("o", ParameterType.CATEGORICAL, values=("a", "b", "c"))
    assert cat.from_unit(0.0) == "a" and cat.from_unit(0.99) == "c"
    assert cat.grid() == ["a", "b", "c"]

    with pytest.raises(ValueError):
        ParameterSpec("bad", ParameterType.DOUBLE, min=1, max=0)
    with pytest.raises(ValueError):
        ParameterSpec("bad", ParameterType.DOUBLE, min=-1, max=1, log_scale=True)
    with pytest.raises(ValueError):
        ParameterSpec("bad", ParameterType.CATEGORICAL)

    # wire roundtrip
    assert ParameterSpec.from_dict(lr.to_dict()) == lr


def test_template_substitution():
    t = {
        "replicas": {
            "worker": {
                "command": ["python", "train.py", "--lr=${trialParameters.lr}"],
                "env": {"LAYERS": "${trialParameters.layers}"},
            }
        }
    }
    out = substitute_template(t, {"lr": 0.01, "layers": 4})
    assert out["replicas"]["worker"]["command"][2] == "--lr=0.01"
    assert out["replicas"]["worker"]["env"]["LAYERS"] == "4"


# ----------------------------------------------------------------- algorithms


def _quadratic(p):
    # optimum at lr=1e-2, layers=4
    return (math.log10(p["lr"]) + 2) ** 2 + (p["layers"] - 4) ** 2 * 0.1


@pytest.mark.parametrize("algo", ["random", "bayesian", "tpe", "cmaes"])
def test_suggesters_beat_worst_case(algo):
    spec = _exp(algo, max_trials=20)
    sug = make_suggester(spec, seed=1)
    history = []
    for _ in range(20):
        for a in sug.suggest(2, history):
            history.append((a.parameters, _quadratic(a.parameters)))
    best = min(v for _, v in history)
    assert best < 1.0  # found the basin (worst case is ~4.9)


def test_model_based_beat_random_on_average():
    """bayesian/tpe must exploit structure: compare best-of-N vs random."""

    def best_of(algo, seed):
        spec = _exp(algo, max_trials=24)
        sug = make_suggester(spec, seed=seed)
        history = []
        for _ in range(12):
            for a in sug.suggest(2, history):
                history.append((a.parameters, _quadratic(a.parameters)))
        return min(v for _, v in history)

    # absolute bars (random-search expectation for best-of-24 is ~0.4;
    # model-based must reliably land deep in the basin on every seed)
    for s in range(3):
        assert best_of("bayesian", s) < 0.5
        assert best_of("tpe", s) < 0.5


def test_grid_exhausts_space():
    spec = ExperimentSpec(
        name="g",
        parameters=(
            ParameterSpec("a", ParameterType.INT, min=0, max=2),
            ParameterSpec("b", ParameterType.CATEGORICAL, values=("x", "y")),
        ),
        objective=Objective("loss"),
        algorithm=AlgorithmSpec("grid"),
    )
    sug = make_suggester(spec)
    got = sug.suggest(100, [])
    assert len(got) == 6  # 3 × 2 grid
    assert sug.suggest(5, []) == []  # exhausted
    combos = {(a.parameters["a"], a.parameters["b"]) for a in got}
    assert len(combos) == 6


def test_hyperband_escalates_budget():
    spec = _exp("hyperband", eta=2, min_budget=1, max_budget=4, parallel=4)
    sug = make_suggester(spec, seed=0)
    first = sug.suggest(4, [])
    assert all(a.parameters["epochs"] == 1 for a in first)
    history = [(a.parameters, _quadratic(a.parameters)) for a in first]
    second = sug.suggest(4, history)
    assert all(a.parameters["epochs"] == 2 for a in second)
    # survivors are the best half of rung 0
    best_rung0 = sorted(history, key=lambda t: t[1])[:2]
    promoted = {tuple(sorted((k, str(v)) for k, v in a.parameters.items() if k != "epochs"))
                for a in second[:2]}
    expected = {tuple(sorted((k, str(v)) for k, v in p.items() if k != "epochs"))
                for p, _ in best_rung0}
    assert promoted == expected


def test_unknown_algorithm_rejected():
    with pytest.raises(ValueError, match="unknown algorithm"):
        make_suggester(_exp("simulated-annealing"))
    # NAS names are known but redirect to the in-process one-shot searcher
    with pytest.raises(ValueError, match="tune.nas"):
        make_suggester(_exp("darts"))


# -------------------------------------------------------------------- metrics


def test_stdout_regex_scraper():
    log = """\
starting up
epoch=1 loss=0.9 accuracy=0.5
epoch=2 loss=0.5 accuracy=0.7
noise line without metrics
step=30 loss=0.3
final: accuracy: 0.91
"""
    series = tmetrics.collect_from_text(log, "loss", ["accuracy"])
    assert series["loss"] == [(1, 0.9), (2, 0.5), (30, 0.3)]
    assert series["accuracy"][-1] == (3, 0.91)  # auto-step when none on line
    assert tmetrics.best(series["loss"], minimize=True) == 0.3
    assert tmetrics.latest(series["accuracy"]) == 0.91


def test_scraper_scientific_notation_and_negative():
    s = tmetrics.collect_from_text("loss=-1.5e-3", "loss")
    assert s["loss"] == [(0, -1.5e-3)]


# --------------------------------------------------------------- early stopping


def _trial(vals, state=TrialState.SUCCEEDED):
    t = Trial(assignment=TrialAssignment({}), state=state)
    t.observations = list(enumerate(vals))
    return t


def test_medianstop():
    obj = Objective("loss", ObjectiveType.MINIMIZE)
    stopper = MedianStop(EarlyStoppingSpec(min_trials_required=3, start_step=2), obj)
    completed = [_trial([1.0, 0.8, 0.5]), _trial([1.0, 0.7, 0.4]), _trial([0.9, 0.6, 0.3])]
    # a trial stuck at 2.0 by step 4 is worse than the median best (0.4) → stop
    bad = _trial([2.0, 2.0, 2.0, 2.0, 2.0], TrialState.RUNNING)
    assert stopper.should_stop(bad, completed)
    good = _trial([0.9, 0.5, 0.2], TrialState.RUNNING)
    assert not stopper.should_stop(good, completed)
    # too few completed trials → never stop
    assert not stopper.should_stop(bad, completed[:2])


# ------------------------------------------------------------------ controller


def test_experiment_controller_reaches_goal():
    spec = _exp("bayesian", goal=0.5, max_trials=40, parallel=4, n_initial=4)
    status = tune(_quadratic, spec, seed=3)
    assert status.complete
    assert status.optimal is not None
    assert status.optimal.metrics["__objective__"] < 0.5
    assert status.reason == "objective goal reached"
    assert len(status.trials) <= spec.max_trial_count + spec.parallel_trial_count


def test_experiment_controller_max_trials_and_failures():
    spec = _exp("random", max_trials=6, parallel=2)
    status = tune(_quadratic, spec)
    assert status.succeeded >= 6 and status.complete

    calls = {"n": 0}

    def flaky(p):
        calls["n"] += 1
        raise RuntimeError("boom")

    spec2 = ExperimentSpec(
        name="f",
        parameters=_space(),
        objective=Objective("loss"),
        max_trial_count=50,
        max_failed_trial_count=3,
        parallel_trial_count=2,
    )
    status2 = tune(flaky, spec2)
    assert status2.reason == "max_failed_trial_count exceeded"
    assert status2.failed >= 4
    assert calls["n"] < 20  # stopped early, didn't burn the whole budget


def test_experiment_grid_exhaustion_completes():
    spec = ExperimentSpec(
        name="gx",
        parameters=(ParameterSpec("a", ParameterType.INT, min=0, max=1),),
        objective=Objective("loss"),
        algorithm=AlgorithmSpec("grid"),
        max_trial_count=50,
        parallel_trial_count=2,
    )
    status = tune(lambda p: float(p["a"]), spec)
    assert status.reason == "search space exhausted"
    assert status.succeeded == 2
    assert status.optimal.assignment.parameters["a"] == 0


def test_callable_runner_accepts_curves_and_dicts():
    r = CallableTrialRunner(lambda p: [(0, 1.0), (1, 0.4)])
    t = Trial(assignment=TrialAssignment({"x": 1}))
    r.run(t, _exp())
    assert t.state is TrialState.SUCCEEDED
    assert t.metrics["__objective__"] == 0.4
    assert t.observations == [(0, 1.0), (1, 0.4)]

    r2 = CallableTrialRunner(lambda p: {"loss": 0.2, "acc": 0.9})
    t2 = Trial(assignment=TrialAssignment({}))
    r2.run(t2, _exp())
    assert t2.metrics["__objective__"] == 0.2 and t2.metrics["acc"] == 0.9


# ------------------------------------------------------------------- gRPC svc


def test_grpc_suggestion_service_roundtrip():
    from kubeflow_tpu.tune.service import RemoteSuggester, SuggestionClient, serve

    server, port = serve(seed=7)
    try:
        client = SuggestionClient(f"127.0.0.1:{port}")
        spec = _exp("tpe", max_trials=10)
        ok, msg = client.validate(spec)
        assert ok, msg
        bad = ExperimentSpec(
            name="bad",
            parameters=_space(),
            objective=Objective("loss"),
            algorithm=AlgorithmSpec("nope"),
        )
        ok, msg = client.validate(bad)
        assert not ok and "unknown algorithm" in msg

        history = []
        for _ in range(4):
            assignments = client.get_suggestions(spec, history, 3)
            assert len(assignments) == 3
            for a in assignments:
                assert set(a.parameters) == {"lr", "layers", "opt"}
                assert 1e-4 <= a.parameters["lr"] <= 1e-1
                history.append((a.parameters, _quadratic(a.parameters)))

        # RemoteSuggester drives a full experiment over the wire
        remote = RemoteSuggester(spec, client)
        ctl = ExperimentController(spec, CallableTrialRunner(_quadratic),
                                   suggester=remote)
        status = ctl.run()
        assert status.succeeded >= spec.max_trial_count
        client.close()
    finally:
        server.stop(0)


# ----------------------------------------------------- orchestrator-backed e2e


def test_job_trial_runner_via_orchestrator(tmp_path):
    """§3.4 analog: trials are jobs; metrics scraped from worker logs."""
    from kubeflow_tpu.orchestrator.cluster import LocalCluster
    from kubeflow_tpu.tune.controller import JobTrialRunner

    template = {
        "replicas": {
            "worker": {
                "replicas": 1,
                "command": [
                    "python",
                    "-c",
                    "import sys; lr=float('${trialParameters.lr}'); "
                    "print(f'step=1 loss={(lr-0.01)**2:.6f}')",
                ],
            }
        },
        "run_policy": {"backoff_limit": 0},
    }
    spec = ExperimentSpec(
        name="jobs",
        parameters=(
            ParameterSpec("lr", ParameterType.DOUBLE, min=1e-3, max=1e-1,
                          log_scale=True),
        ),
        objective=Objective("loss", ObjectiveType.MINIMIZE),
        algorithm=AlgorithmSpec("random"),
        parallel_trial_count=2,
        max_trial_count=4,
        trial_template=template,
    )
    with LocalCluster(base_dir=tmp_path) as cluster:
        runner = JobTrialRunner(cluster, timeout_s=60)
        status = ExperimentController(spec, runner, seed=5).run()
    assert status.succeeded == 4, [t.message for t in status.trials]
    assert status.optimal is not None
    assert status.optimal.metrics["loss"] >= 0
