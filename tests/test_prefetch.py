"""train/prefetch.py: producer/drain thread semantics.

These are host-side contracts (ordering, bounded depth, error carry,
clean joins) — the trainer-integration side lives in tests/test_train.py.
"""

import io
import threading
import time

import numpy as np
import pytest

from kubeflow_tpu.train.metrics import MetricWriter, NonFiniteMetricError
from kubeflow_tpu.train.prefetch import (
    DevicePrefetcher,
    InlineFetcher,
    MetricsDrain,
    live_kft_threads,
    make_fetcher,
)


def test_prefetcher_preserves_order_and_stops():
    pf = DevicePrefetcher(range(5), lambda x: x * 10, depth=2)
    assert list(pf) == [0, 10, 20, 30, 40]
    assert live_kft_threads() == []  # StopIteration closed + joined


def test_prefetcher_bounded_depth():
    produced = []
    done = threading.Event()

    def source():
        for i in range(100):
            produced.append(i)
            yield i
        done.set()

    pf = DevicePrefetcher(source(), lambda x: x, depth=3)
    time.sleep(0.3)  # let the producer run as far ahead as it can
    # nothing consumed: at most depth queued + 1 in flight in place()
    assert len(produced) <= 3 + 1
    assert not done.is_set()
    consumed = [next(pf) for _ in range(10)]
    assert consumed == list(range(10))
    pf.close()
    assert live_kft_threads() == []


def test_prefetcher_carries_producer_error():
    def source():
        yield 1
        raise ValueError("bad shard")

    pf = DevicePrefetcher(source(), lambda x: x, depth=2)
    assert next(pf) == 1
    with pytest.raises(ValueError, match="bad shard"):
        next(pf)
    assert live_kft_threads() == []


def test_prefetcher_place_error_propagates():
    def place(x):
        if x == 2:
            raise RuntimeError("H2D failed")
        return x

    pf = DevicePrefetcher(range(5), place, depth=2)
    assert next(pf) == 0
    assert next(pf) == 1
    with pytest.raises(RuntimeError, match="H2D failed"):
        next(pf)
    assert live_kft_threads() == []


def test_prefetcher_close_unblocks_parked_producer():
    def forever():
        i = 0
        while True:
            yield i
            i += 1

    pf = DevicePrefetcher(forever(), lambda x: x, depth=1)
    assert next(pf) == 0
    pf.close()  # producer is parked on a full queue right now
    pf.close()  # idempotent
    assert live_kft_threads() == []


def test_window_stats_reset_between_windows():
    pf = DevicePrefetcher(range(6), lambda x: x, depth=2)
    for _ in range(3):
        next(pf)
    w1 = pf.window_stats()
    assert set(w1) == {"data_stall_ms", "h2d_ms"}
    assert w1["data_stall_ms"] >= 0 and w1["h2d_ms"] >= 0
    w2 = pf.window_stats()  # nothing consumed since: zeros
    assert w2["data_stall_ms"] == 0 and w2["h2d_ms"] == 0
    pf.close()


def test_inline_fetcher_same_interface():
    f = make_fetcher(range(3), lambda x: x + 1, depth=0)
    assert isinstance(f, InlineFetcher)
    assert [next(f), next(f), next(f)] == [1, 2, 3]
    stats = f.window_stats()
    assert set(stats) == {"data_stall_ms", "h2d_ms"}
    with pytest.raises(StopIteration):
        next(f)
    f.close()


def test_drain_writes_logged_windows_in_order():
    out = io.StringIO()
    history: list[dict] = []
    hooked: list[int] = []
    with MetricWriter(None, stdout=out) as w:
        drain = MetricsDrain(
            w, history=history, hooks=[lambda s, m: hooked.append(s)]
        )
        for step in range(1, 7):
            drain.put(
                step,
                {"loss": np.float32(step)},
                log=step % 2 == 0,
                extra={"data_stall_ms": 1.0} if step % 2 == 0 else None,
            )
        drain.close()
    assert [h["step"] for h in history] == [2, 4, 6]
    assert [h["loss"] for h in history] == [2.0, 4.0, 6.0]
    assert all("data_stall_ms" in h for h in history)
    assert hooked == [2, 4, 6]
    assert "step=2 loss=2" in out.getvalue()


def test_drain_nan_alarm_bounded_lag_no_deadlock():
    w = MetricWriter(None, stdout=io.StringIO(), nan_alarm=True)
    drain = MetricsDrain(w, history=[], depth=4)
    drain.put(1, {"loss": np.float32("nan")}, log=True)
    # the failed drain must keep discarding, never deadlock the producer
    for step in range(2, 40):
        drain.put(step, {"loss": np.float32(step)}, log=True)
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            drain.poll()
        except NonFiniteMetricError:
            break
        time.sleep(0.01)
    else:
        pytest.fail("NaN alarm never surfaced via poll()")
    drain.shutdown()  # no-raise path after the error was surfaced
    assert live_kft_threads() == []


def test_drain_close_surfaces_pending_error_once():
    w = MetricWriter(None, stdout=io.StringIO(), nan_alarm=True)
    drain = MetricsDrain(w, history=[])
    drain.put(3, {"loss": np.float32("inf")}, log=True)
    with pytest.raises(NonFiniteMetricError, match="step 3"):
        drain.close()
    drain.shutdown()  # already raised: must not raise again
    drain.poll()
