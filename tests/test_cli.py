"""kft CLI (kubeflow_tpu/cli.py): the kubectl/kfp-CLI analog. Every
subcommand is driven the way a user would — `run` and `build` in-process
through main(argv), `serve` as a real `python -m kubeflow_tpu` subprocess
answering REST on a bound port."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest
import yaml

from kubeflow_tpu.cli import main

def _pod(command):
    return {"spec": {"containers": [{"command": list(command)}]}}


JOB_OK = {
    "apiVersion": "kubeflow.org/v1",
    "kind": "JAXJob",
    "metadata": {"name": "hello"},
    "spec": {
        "replicaSpecs": {
            "Worker": {
                "replicas": 2,
                "template": _pod(
                    [sys.executable, "-c", "print('step=1 loss=0.5')"]
                ),
            }
        }
    },
}


def _write_yaml(tmp_path, doc, name="m.yaml"):
    p = tmp_path / name
    p.write_text(yaml.safe_dump(doc))
    return str(p)


def test_run_job_success_exit_zero(tmp_path, capsys):
    rc = main(["run", "-f", _write_yaml(tmp_path, JOB_OK), "--timeout", "60"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "job/hello: Succeeded" in out


def test_run_job_failure_exit_nonzero_and_logs(tmp_path, capsys):
    bad = yaml.safe_load(yaml.safe_dump(JOB_OK))
    bad["metadata"]["name"] = "boom"
    bad["spec"]["replicaSpecs"]["Worker"]["replicas"] = 1
    bad["spec"]["replicaSpecs"]["Worker"]["template"] = _pod(
        [sys.executable, "-c", "import sys; print('dying'); sys.exit(3)"]
    )
    bad["spec"]["runPolicy"] = {"backoffLimit": 0}
    rc = main(["run", "-f", _write_yaml(tmp_path, bad), "--timeout", "60"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "job/boom: Failed" in out
    assert "dying" in out  # failure logs streamed without --logs


def test_run_experiment_prints_best(tmp_path, capsys):
    exp = {
        "apiVersion": "kubeflow.org/v1beta1",
        "kind": "Experiment",
        "metadata": {"name": "sweep"},
        "spec": {
            "parameters": [
                {"name": "lr", "type": "double", "min": 0.001, "max": 0.1,
                 "log_scale": True},
            ],
            "objective": {"metric": "loss", "type": "minimize"},
            "algorithm": {"name": "random"},
            "parallel_trial_count": 2,
            "max_trial_count": 4,
            "trial_template": {
                "replicas": {
                    "worker": {
                        "replicas": 1,
                        "command": [
                            sys.executable, "-c",
                            "lr=float('${trialParameters.lr}'); "
                            "print(f'step=1 loss={lr*2}')",
                        ],
                    }
                },
                "run_policy": {"backoff_limit": 0},
            },
        },
    }
    rc = main(["run", "-f", _write_yaml(tmp_path, exp), "--timeout", "120"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "experiment/sweep: trials=4 best=" in out


def test_run_rejects_isvc(tmp_path, capsys):
    isvc = {
        "apiVersion": "serving.kserve.io/v1beta1",
        "kind": "InferenceService",
        "metadata": {"name": "m"},
        "spec": {"predictor": {"model": {"modelFormat": {"name": "bert"}}}},
    }
    rc = main(["run", "-f", _write_yaml(tmp_path, isvc)])
    assert rc == 2
    assert "kft serve" in capsys.readouterr().err


def test_build_resolves_overlay(capsys):
    rc = main(["build", "kubeflow_tpu/examples/manifests/overlays/dev"])
    assert rc == 0
    docs = list(yaml.safe_load_all(capsys.readouterr().out))
    assert docs and all("kind" in d for d in docs)


def test_doctor_reports_backend(capsys):
    rc = main(["doctor", "--timeout", "120"])
    report = json.loads(capsys.readouterr().out)
    assert "backend" in report
    assert rc in (0, 1)
    if rc == 0:
        assert report["devices"] >= 1


def test_serve_subprocess_answers_rest(tmp_path):
    """`python -m kubeflow_tpu serve -f isvc.yaml` — real process, real
    port, real storage-initializer pull of an xgboost checkpoint."""
    model_src = tmp_path / "src"
    model_src.mkdir()
    (model_src / "model.json").write_text(json.dumps({
        "version": [2, 0, 0],
        "learner": {
            "learner_model_param": {
                "base_score": "0.0", "num_class": "0", "num_feature": "1"},
            "objective": {"name": "reg:squarederror"},
            "gradient_booster": {"model": {
                "trees": [{
                    "split_indices": [0, 0, 0],
                    "split_conditions": [0.5, 1.0, -3.0],
                    "left_children": [1, -1, -1],
                    "right_children": [2, -1, -1],
                    "default_left": [True, False, False],
                    "base_weights": [0.0, 0.0, 0.0],
                    "tree_param": {"num_nodes": "3"},
                }],
                "tree_info": [0],
            }},
        },
    }))
    isvc = {
        "apiVersion": "serving.kserve.io/v1beta1",
        "kind": "InferenceService",
        "metadata": {"name": "gbt"},
        "spec": {"predictor": {"model": {
            "modelFormat": {"name": "xgboost"},
            "storageUri": f"file://{model_src}",
        }}},
    }
    graph = {
        "apiVersion": "serving.kserve.io/v1alpha1",
        "kind": "InferenceGraph",
        "metadata": {"name": "g"},
        "spec": {"nodes": {"root": {
            "routerType": "Sequence",
            "steps": [{"serviceName": "gbt"}],
        }}},
    }
    manifest = tmp_path / "m.yaml"
    manifest.write_text(
        yaml.safe_dump(isvc) + "---\n" + yaml.safe_dump(graph)
    )
    port_file = tmp_path / "port"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu", "serve",
         "-f", str(manifest),
         "--http-port", "0", "--port-file", str(port_file),
         "--model-dir", str(tmp_path / "mnt")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        # generous: under a full parallel suite on a 1-cpu host, the
        # subprocess's jax import + model load alone can take >60s
        deadline = time.time() + 240
        while not port_file.exists() and time.time() < deadline:
            assert proc.poll() is None, proc.stdout.read()
            time.sleep(0.1)
        assert port_file.exists(), "server never wrote the port file"
        port = int(port_file.read_text())
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/gbt:predict",
            data=json.dumps({"instances": [[0.0], [2.0]]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            body = json.loads(r.read())
        assert body["predictions"] == [1.0, -3.0]
        # the InferenceGraph doc in the same manifest serves too
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/graphs/g:infer",
            data=json.dumps({"instances": [[0.0], [2.0]]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            body = json.loads(r.read())
        assert body["predictions"] == [1.0, -3.0]
    finally:
        proc.terminate()
        proc.wait(10)
