"""In-graph speculative decoding (serve/speculative.py + engine spec mode):
SPECULATION IS A SCHEDULING OPTIMIZATION, NEVER A NUMERICS CHANGE. Greedy
decode with ``spec_draft_tokens=K`` must be byte-identical to K=0 (which is
itself pinned to the whole-batch generate path) across dense/paged ×
inline/pipelined, under admission churn, chunked prefill, prefix caching
and cancellation; temperature>0 must be seed-deterministic via the
distribution-preserving rejection rule."""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM
from kubeflow_tpu.serve.engine import LMEngine
from kubeflow_tpu.serve.generate import make_generate_fn

CFG = TransformerConfig(
    vocab_size=89, d_model=32, n_layers=2, n_heads=4, d_ff=64,
    causal=True, max_seq_len=256, attn_impl="reference", dtype=jnp.float32,
)
EOS = 1


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return model, params


def _prompts(rng, n, lo=3, hi=20):
    return [
        [int(x) for x in rng.integers(2, CFG.vocab_size, size=rng.integers(lo, hi))]
        for _ in range(n)
    ]


def _mk(model, params, *, spec=4, paged=False, depth=1, **kw):
    base = dict(
        max_batch=3, max_seq=96, chunk_steps=4, prefill_buckets=(32,),
        eos_id=EOS, pipeline_depth=depth, spec_draft_tokens=spec, seed=7,
    )
    base.update(kw)
    if paged:
        base.setdefault("kv_pool_tokens", 16 * 20)
        base.setdefault("page_size", 16)
    return LMEngine(model, CFG, params, **base).start()


# ----------------------------------------------------------- drafter unit


def test_propose_draft_matches_and_degrades():
    from kubeflow_tpu.serve.speculative import propose_draft

    hist = jnp.asarray([
        # periodic row: ...5 6 7 5 6 7 5 6 7 (L=9) → ctx [7,5,6]? no:
        # last 3 = [5,6,7] at 6..8; full-window match at 0 → draft 5 6 7 5
        [5, 6, 7, 5, 6, 7, 5, 6, 7, 0, 0, 0],
        # no repetition: no match
        [2, 3, 4, 5, 6, 7, 8, 9, 10, 0, 0, 0],
        # too little history for ngram+1
        [4, 4, 4, 0, 0, 0, 0, 0, 0, 0, 0, 0],
    ], jnp.int32)
    hist_len = jnp.asarray([9, 9, 2], jnp.int32)
    draft, draft_len = propose_draft(hist, hist_len, ngram=3, k=4)
    draft, draft_len = np.asarray(draft), np.asarray(draft_len)
    assert draft_len[0] == 4
    # continuation after the EARLIEST [5,6,7] occurrence (full window):
    # positions 3..6 → [5, 6, 7, 5]
    assert list(draft[0]) == [5, 6, 7, 5]
    assert draft_len[1] == 0
    assert draft_len[2] == 0


def test_propose_draft_prefers_recent_full_window():
    from kubeflow_tpu.serve.speculative import propose_draft

    # [1 2 3 9 9] then [1 2 3 4 4] then context [1 2 3]: the most recent
    # full-window match (start 5) wins over the older one (start 0)
    row = [1, 2, 3, 9, 9, 1, 2, 3, 4, 4, 1, 2, 3]
    hist = jnp.asarray([row + [0] * 3], jnp.int32)
    draft, draft_len = propose_draft(
        hist, jnp.asarray([len(row)], jnp.int32), ngram=3, k=2
    )
    assert int(draft_len[0]) == 2
    assert list(np.asarray(draft)[0]) == [4, 4]


# ------------------------------------------------------------- parity core


def test_spec_greedy_byte_identical_all_modes(model_and_params):
    """The tentpole contract: spec_draft_tokens=4 produces byte-identical
    greedy token streams to spec_draft_tokens=0 across dense/paged ×
    inline/pipelined — including prompts engineered to draft heavily
    (repetitive) and prompts that rarely match."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    prompts = _prompts(rng, 4) + [[7, 8, 9] * 6, [11, 12] * 9]
    base = _mk(model, params, spec=0)
    try:
        want = {i: base.submit(p, max_new_tokens=12) for i, p in enumerate(prompts)}
    finally:
        base.stop()
    for paged in (False, True):
        for depth in (0, 1):
            eng = _mk(model, params, spec=4, paged=paged, depth=depth)
            try:
                for i, p in enumerate(prompts):
                    got = eng.submit(p, max_new_tokens=12)
                    assert got == want[i], (paged, depth, i, got, want[i])
                assert eng.stats["spec_proposed"] >= 0
            finally:
                eng.stop()


def test_spec_matches_whole_batch_reference(model_and_params):
    """Speculative completions equal the pinned make_generate_fn path —
    not just the non-spec engine (no shared-bug blind spot)."""
    model, params = model_and_params
    gen = jax.jit(
        make_generate_fn(model, CFG, max_new_tokens=12, eos_id=EOS)
    )
    eng = _mk(model, params, spec=4)
    try:
        rng = np.random.default_rng(3)
        for ids in _prompts(rng, 5):
            prompt = np.zeros((1, 32), np.int32)
            prompt[0, : len(ids)] = ids
            toks, n_valid = gen(
                params, prompt, np.asarray([len(ids)], np.int32),
                jax.random.PRNGKey(7), np.zeros((1,), np.float32),
            )
            want = [int(t) for t in np.asarray(toks)[0, : int(n_valid[0])]]
            assert eng.submit(ids, max_new_tokens=12) == want, ids
    finally:
        eng.stop()


def test_spec_parity_under_admission_churn_and_cancellation(
    model_and_params,
):
    """Spec decode under the full engine life: staggered concurrent
    requests through fewer rows (churn + epochs), chunked prefill pieces
    interleaving with speculative chunks, and a mid-stream cancellation.
    Tokens identical to the non-spec engine, pipelined and inline."""
    model, params = model_and_params
    rng = np.random.default_rng(71)
    prompts = _prompts(rng, 5, lo=3, hi=14) + [
        [int(x) for x in rng.integers(2, CFG.vocab_size, size=n)]
        for n in (34, 41)
    ]

    def run_mode(spec, depth):
        eng = _mk(
            model, params, spec=spec, depth=depth, max_seq=112,
            prefill_buckets=(48,), prefill_chunk=16,
        )
        outs: dict[int, list[int]] = {}
        errors: list[Exception] = []

        def worker(i):
            try:
                time.sleep(0.02 * i)
                outs[i] = eng.submit(prompts[i], max_new_tokens=12)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        try:
            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            stream = eng.stream(prompts[0], max_new_tokens=12)
            next(iter(stream))
            stream.close()
            for t in threads:
                t.join(180)
            stats = dict(eng.stats)
        finally:
            eng.stop()
        assert not errors, errors
        return outs, stats

    want, _ = run_mode(0, 1)
    for depth in (0, 1):
        got, stats = run_mode(4, depth)
        assert got == want, (depth, got, want)
        assert stats["max_concurrent"] >= 2
        assert stats["prefill_pieces"] > len(prompts)


def test_spec_with_prefix_cache_parity(model_and_params):
    """Prefix-cache hits implant KV and the history mirror must still be
    exact (it is host data either way): spec completions with reuse equal
    non-spec completions with reuse."""
    model, params = model_and_params
    outs = {}
    for spec in (0, 4):
        eng = _mk(
            model, params, spec=spec, max_batch=1,
            prefix_cache_entries=4,
        )
        try:
            rng = np.random.default_rng(11)
            base = [int(x) for x in rng.integers(2, CFG.vocab_size, size=20)]
            outs[spec] = [eng.submit(base, max_new_tokens=10)]
            for tail in ([3, 4], [5, 6, 7]):
                outs[spec].append(
                    eng.submit(base[:16] + tail, max_new_tokens=10)
                )
            assert eng.stats["prefix_hits"] == 2
        finally:
            eng.stop()
    assert outs[0] == outs[4]


def test_spec_no_match_rows_emit_one_token_per_step(model_and_params):
    """Rows whose history never matches the n-gram context must degrade
    to classic one-token steps: zero proposals, and the same number of
    decode chunks as the non-spec engine (no wasted verify width)."""
    model, params = model_and_params
    # all-distinct prompt, tiny budget: nothing for the drafter to match
    ids = list(range(2, 22))
    chunks, outs = {}, {}
    for spec in (0, 4):
        eng = _mk(model, params, spec=spec, max_batch=1)
        try:
            outs[spec] = eng.submit(ids, max_new_tokens=4)
            chunks[spec] = eng.stats["chunks"]
            if spec:
                assert eng.stats["spec_proposed"] == 0
                assert eng.stats["spec_accepted"] == 0
        finally:
            eng.stop()
    assert outs[4] == outs[0]
    assert chunks[4] == chunks[0]


def test_spec_acceptance_counters_and_fewer_chunks(model_and_params):
    """A strongly repetitive greedy continuation must actually accept
    drafts: counters move and the same tokens cost fewer chunks. The
    copy-deterministic model (attention/MLP write-back zeroed) makes the
    greedy chain periodic, so acceptance is structural, not luck."""
    import flax

    model, params = model_and_params
    flat = flax.traverse_util.flatten_dict(params)
    cp = flax.traverse_util.unflatten_dict({
        k: (jnp.zeros_like(v) if k[-2] in ("o_proj", "down_proj") else v)
        for k, v in flat.items()
    })
    ids = [5, 6, 7, 8] * 4
    results = {}
    for spec in (0, 4):
        eng = LMEngine(
            model, CFG, cp, max_batch=1, max_seq=160, chunk_steps=2,
            prefill_buckets=(32,), eos_id=CFG.vocab_size + 1,
            spec_draft_tokens=spec,
        ).start()
        try:
            out = eng.submit(ids, max_new_tokens=64)
            results[spec] = (out, eng.stats["chunks"],
                             eng.stats["spec_accepted"])
        finally:
            eng.stop()
    out0, chunks0, _ = results[0]
    out4, chunks4, accepted = results[4]
    assert out4 == out0
    assert accepted > 0
    # the acceptance bar: >= 1.5x fewer forwards for the same tokens
    assert chunks0 >= 1.5 * chunks4, (chunks0, chunks4)


# ------------------------------------------------------------ temperature


def test_spec_temperature_seeded_determinism(model_and_params):
    """temperature>0 under speculation: rejection sampling must be
    deterministic per engine seed — two fresh engines, same seed, same
    requests → identical streams; a different seed may diverge."""
    model, params = model_and_params

    def run(seed):
        eng = _mk(model, params, spec=4, seed=seed)
        try:
            return [
                eng.submit([7, 8, 9] * 4, max_new_tokens=16, temperature=0.8),
                eng.submit([3, 4] * 6, max_new_tokens=10, temperature=1.3),
            ]
        finally:
            eng.stop()

    a, b = run(7), run(7)
    assert a == b
    for toks in a:
        assert toks and all(0 <= t < CFG.vocab_size for t in toks)


def test_spec_mixed_greedy_and_sampled_rows(model_and_params):
    """Greedy rows co-batched with sampling rows: the greedy row's stream
    must STILL equal the non-spec greedy reference (per-row temperature
    semantics survive the span-verify path)."""
    model, params = model_and_params
    base = _mk(model, params, spec=0)
    try:
        want = base.submit([5, 9, 33, 60, 2], max_new_tokens=12)
    finally:
        base.stop()
    eng = _mk(model, params, spec=4)
    try:
        results = {}

        def sampled():
            results["s"] = eng.submit(
                [7, 8, 9] * 4, max_new_tokens=12, temperature=1.0
            )

        th = threading.Thread(target=sampled)
        th.start()
        results["g"] = eng.submit([5, 9, 33, 60, 2], max_new_tokens=12)
        th.join(120)
    finally:
        eng.stop()
    assert results["g"] == want
    assert len(results["s"]) > 0


# ------------------------------------------------------------- validation


def test_spec_config_validation(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="spec_draft_tokens"):
        LMEngine(model, CFG, params, max_batch=1, spec_draft_tokens=-1)
    with pytest.raises(ValueError, match="spec_ngram"):
        LMEngine(
            model, CFG, params, max_batch=1, spec_draft_tokens=2,
            spec_ngram=0,
        )
    # ngram knob is inert while spec is off — no validation error
    eng = LMEngine(
        model, CFG, params, max_batch=1, max_seq=64,
        prefill_buckets=(32,), spec_draft_tokens=0, spec_ngram=0,
    )
    assert eng.spec_k == 0


def test_spec_dense_headroom_enforced_at_enqueue(model_and_params):
    """Dense spec reserves K scratch KV slots: a request that fits without
    them but not with them must fail fast at submit."""
    model, params = model_and_params
    eng = _mk(
        model, params, spec=4, max_batch=1, max_seq=40,
        prefill_buckets=(32,),
    )
    try:
        with pytest.raises(ValueError, match="spec_draft_tokens"):
            eng.submit([3, 4, 5], max_new_tokens=8)  # 32+8+4 > 40
        out = eng.submit([3, 4, 5], max_new_tokens=4)  # 32+4+4 ≤ 40
        assert isinstance(out, list)
    finally:
        eng.stop()


def test_spec_engine_model_warmup_resets_spec_metrics(model_and_params):
    """LMEngineModel.warmup with spec on compiles the verify program and
    leaves every spec counter at zero — warmup traffic must not pollute
    the acceptance gauges."""
    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec

    model, params = model_and_params
    m = LMEngineModel(
        "lm", None, config=CFG, max_batch=2, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=8, eos_id=EOS, spec_draft_tokens=4,
    )
    m.load()
    try:
        m._params = jax.device_put(params)
        m.warmup()
        eng = m.engine
        assert eng.spec_k == 4
        assert eng.stats["spec_proposed"] == 0
        assert eng.stats["spec_accepted"] == 0
        assert eng.overlap["spec_acceptance"] == 0.0
        # and the engine still serves correctly after the reset
        out = m.engine.submit([4, 8, 15], max_new_tokens=4)
        assert isinstance(out, list)
    finally:
        m.unload()


# -------------------------------------------------------------- satellites


def test_prefix_lens_sorted_cache_invalidation(model_and_params):
    """The memoized descending length list must track store/evict — a
    stale cache would silently miss (or ghost-probe) prefix lengths."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=1, max_seq=96, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS, prefix_cache_entries=2,
    ).start()
    try:
        rng = np.random.default_rng(13)
        a = [int(x) for x in rng.integers(2, CFG.vocab_size, size=18)]
        eng.submit(a, max_new_tokens=4)  # stores a[:16]
        eng.submit(a[:16] + [7, 8], max_new_tokens=4)
        assert eng.stats["prefix_hits"] == 1
        assert eng._prefix_lens_sorted == [16]
        # eviction pressure: two new distinct prefixes evict the first
        for _ in range(2):
            ids = [int(x) for x in rng.integers(2, CFG.vocab_size, size=18)]
            eng.submit(ids, max_new_tokens=4)
        # cache coherent: sorted view equals a fresh sort of the truth
        probe = sorted(eng._prefix_lens, reverse=True)
        eng._lookup_prefix(a)  # forces rebuild if invalidated
        assert eng._prefix_lens_sorted == probe
    finally:
        eng.stop()


def test_spec_and_prefix_metrics_on_server(model_and_params):
    """/metrics exports kft_engine_prefix_* and kft_engine_spec_* for
    engine-backed models — the gateway's prefix affinity and the
    speculation dashboards read these."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer

    model, params = model_and_params
    m = LMEngineModel(
        "lm", None, config=CFG, max_batch=2, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=6, eos_id=EOS, spec_draft_tokens=4,
        prefix_cache_entries=4,
    )
    m.load()
    m._params = jax.device_put(params)
    server = ModelServer([m])

    async def drive():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post(
                "/v1/models/lm:predict",
                json={"instances": [{"input_ids": [5, 6, 7] * 6}]},
            )
            assert r.status == 200
            return await (await client.get("/metrics")).text()

    try:
        text = asyncio.run(drive())
    finally:
        m.unload()
    for name in (
        "kft_engine_prefix_hits_total",
        "kft_engine_prefix_tokens_reused_total",
        "kft_engine_prefix_entries",
        "kft_engine_prefix_tokens_stored",
        "kft_engine_spec_proposed_total",
        "kft_engine_spec_accepted_total",
        "kft_engine_spec_acceptance",
    ):
        assert f'{name}{{model="lm"}}' in text, name
