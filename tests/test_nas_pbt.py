"""PBT (lineage-aware population training) + DARTS one-shot NAS
(SURVEY.md §2.3 suggestion-service rows: pbt, nas/darts)."""

import numpy as np
import pytest

from kubeflow_tpu.tune.controller import ExperimentController, CallableTrialRunner
from kubeflow_tpu.tune.spec import (
    AlgorithmSpec,
    ExperimentSpec,
    Objective,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    Trial,
    TrialAssignment,
    TrialState,
)
from kubeflow_tpu.tune.suggest import PBTSuggester, make_suggester


def _pbt_spec(**settings):
    return ExperimentSpec(
        name="pbt-e",
        parameters=(
            ParameterSpec("lr", ParameterType.DOUBLE, min=1e-4, max=1e-1,
                          log_scale=True),
            ParameterSpec("opt", ParameterType.CATEGORICAL,
                          values=("sgd", "adam")),
        ),
        objective=Objective("loss", ObjectiveType.MINIMIZE),
        algorithm=AlgorithmSpec("pbt", {"population": 4, **settings}),
        parallel_trial_count=4,
        max_trial_count=16,
    )


def _done_trial(params, value):
    t = Trial(assignment=TrialAssignment(dict(params)))
    t.state = TrialState.SUCCEEDED
    t.metrics["__objective__"] = value
    return t


def test_pbt_cold_start_is_random_without_parent():
    sug = make_suggester(_pbt_spec(), seed=0)
    assert isinstance(sug, PBTSuggester)
    out = sug.suggest_trials(4, [])
    assert len(out) == 4
    assert all(a.parameters["parent_trial"] == "" for a in out)


def test_pbt_exploits_top_quantile_with_lineage():
    sug = make_suggester(_pbt_spec(quantile=0.25), seed=1)
    trials = [
        _done_trial({"lr": 1e-2, "opt": "adam"}, 0.1),  # best
        _done_trial({"lr": 1e-3, "opt": "sgd"}, 0.5),
        _done_trial({"lr": 1e-4, "opt": "sgd"}, 0.9),
        _done_trial({"lr": 5e-2, "opt": "adam"}, 1.5),  # worst
    ]
    best_id = trials[0].assignment.trial_id
    out = sug.suggest_trials(8, trials)
    # quantile 0.25 of 4 → only the best trial is a parent
    assert all(a.parameters["parent_trial"] == best_id for a in out)
    # exploration actually perturbs: not every child keeps the parent's lr
    lrs = {a.parameters["lr"] for a in out}
    assert len(lrs) > 1
    for a in out:
        assert 1e-4 <= a.parameters["lr"] <= 1e-1  # stays in bounds


def test_pbt_maximize_objective_picks_highest():
    spec = ExperimentSpec(
        name="pbt-max",
        parameters=(
            ParameterSpec("lr", ParameterType.DOUBLE, min=0.0, max=1.0),
        ),
        objective=Objective("acc", ObjectiveType.MAXIMIZE),
        algorithm=AlgorithmSpec("pbt", {"population": 2, "quantile": 0.5}),
        parallel_trial_count=2,
    )
    sug = make_suggester(spec, seed=0)
    lo, hi = _done_trial({"lr": 0.2}, 0.3), _done_trial({"lr": 0.8}, 0.9)
    out = sug.suggest_trials(4, [lo, hi])
    assert all(
        a.parameters["parent_trial"] == hi.assignment.trial_id for a in out
    )


def test_pbt_end_to_end_improves():
    """Full controller loop: objective is minimized at lr=0.01; PBT's
    generations should concentrate near it."""
    spec = _pbt_spec(quantile=0.5)

    def objective(params):
        return abs(np.log10(params["lr"]) - np.log10(1e-2))

    status = ExperimentController(
        spec, CallableTrialRunner(objective), seed=3
    ).run()
    assert status.complete
    gen0 = [t for t in status.trials
            if t.assignment.parameters["parent_trial"] == ""]
    children = [t for t in status.trials
                if t.assignment.parameters["parent_trial"] != ""]
    assert children, "PBT never produced a lineage generation"
    best = status.optimal.metrics["__objective__"]
    assert best <= min(t.metrics["__objective__"] for t in gen0)


# ------------------------------------------------------------------- DARTS


def test_nas_space_validation_and_edges():
    from kubeflow_tpu.tune.nas import NASSpace

    sp = NASSpace(nodes=3)
    assert len(sp.edges) == 1 + 2 + 3
    with pytest.raises(ValueError, match="unknown ops"):
        NASSpace(ops=("conv3", "wormhole"))


@pytest.mark.slow
def test_darts_search_commits_to_architecture():
    from kubeflow_tpu.tune.nas import DARTSSearcher, NASSpace

    space = NASSpace(
        ops=("conv3", "skip", "zero"), nodes=2, channels=8, num_classes=4
    )
    searcher = DARTSSearcher(space, seed=0)
    ent0 = searcher.alpha_entropy()

    rng = np.random.RandomState(0)
    protos = rng.randn(4, 8, 8, 1).astype(np.float32)

    def data(step):
        def batch(seed):
            r = np.random.RandomState(seed)
            y = r.randint(0, 4, size=16)
            x = protos[y] + 0.3 * r.randn(16, 8, 8, 1).astype(np.float32)
            return {"image": x.astype(np.float32), "label": y}

        return batch(step * 2), batch(step * 2 + 1)

    losses = [searcher.step(*data(i)) for i in range(40)]
    assert losses[-1]["w_loss"] < losses[0]["w_loss"]  # supernet learns
    assert searcher.alpha_entropy() < ent0  # alphas commit

    cell = searcher.derive()
    assert cell.edges, "derivation kept no edges"
    for i, j, op in cell.edges:
        assert 0 <= i < j <= space.nodes
        assert op in ("conv3", "skip")  # zero is never derived
    # node with >2 incoming candidates keeps exactly 2 (DARTS rule)
    node2 = [e for e in cell.edges if e[1] == 2]
    assert len(node2) == 2
    assert cell.to_dict()["edges"]


@pytest.mark.slow
def test_enas_search_learns_and_derives():
    """ENAS (SURVEY.md §2.3 NAS row, the other half next to DARTS): the
    shared supernet learns through sampled paths, the REINFORCE
    controller's reward improves over the random-policy start, and the
    greedy rollout derives a valid cell in the same DerivedCell shape."""
    from kubeflow_tpu.tune.nas import ENASSearcher, NASSpace

    space = NASSpace(
        ops=("conv3", "skip", "zero"), nodes=2, channels=8, num_classes=4
    )
    searcher = ENASSearcher(space, seed=0)

    rng = np.random.RandomState(0)
    protos = rng.randn(4, 8, 8, 1).astype(np.float32)

    def data(step):
        def batch(seed):
            r = np.random.RandomState(seed)
            y = r.randint(0, 4, size=16)
            x = protos[y] + 0.3 * r.randn(16, 8, 8, 1).astype(np.float32)
            return {"image": x.astype(np.float32), "label": y}

        return batch(step * 2), batch(step * 2 + 1)

    hist = [searcher.step(*data(i)) for i in range(40)]
    assert hist[-1]["w_loss"] < hist[0]["w_loss"]  # shared weights learn
    # reward (val accuracy of sampled paths) beats the early average
    early = np.mean([h["reward"] for h in hist[:5]])
    late = np.mean([h["reward"] for h in hist[-5:]])
    assert late > early, (early, late)
    assert 0.0 < hist[-1]["baseline"] <= 1.0

    cell = searcher.derive()
    assert cell.edges, "greedy rollout derived no edges"
    for i, j, op in cell.edges:
        assert 0 <= i < j <= space.nodes
        assert op in space.ops
    # each node keeps at most 2 incoming edges (two controller slots)
    for j in (1, 2):
        assert 1 <= len([e for e in cell.edges if e[1] == j]) <= 2
    # derive is deterministic (greedy, fixed rng)
    assert searcher.derive().to_dict() == cell.to_dict()


def test_enas_controller_masks_invalid_inputs():
    """Node j may only take inputs from nodes < j — across many sampled
    rollouts no invalid edge ever appears."""
    import jax

    from kubeflow_tpu.tune.nas import ControllerNet, NASSpace

    space = NASSpace(nodes=3, channels=8)
    ctrl = ControllerNet(space)
    params = ctrl.init(jax.random.PRNGKey(0), jax.random.PRNGKey(0))
    roll = jax.jit(lambda rng: ctrl.apply(params, rng))
    for s in range(20):
        inputs, ops, logp, ent = roll(jax.random.PRNGKey(s))
        inputs = np.asarray(inputs)
        for j in range(1, space.nodes + 1):
            assert (inputs[j - 1] < j).all(), (j, inputs)
        assert float(ent) > 0.0
        assert float(logp) < 0.0
