"""Unit tests: spec semantics, store watches, fleet claims, gang queueing.

The table-driven-unit-test tier of the reference's strategy (SURVEY.md §4
"Go unit tests": reconcile math, env construction, gang PodGroup logic —
tested in isolation, no processes).
"""

import sys

import pytest

from kubeflow_tpu.orchestrator import envwire
from kubeflow_tpu.orchestrator.gang import GangScheduler, PodGroup
from kubeflow_tpu.orchestrator.resources import Fleet, Slice, parse_topology, topology_chips
from kubeflow_tpu.orchestrator.spec import (
    JobSpec,
    ReplicaSpec,
    RestartPolicy,
    TPURequest,
)
from kubeflow_tpu.orchestrator.store import ObjectStore

PY = sys.executable


# --------------------------- spec ------------------------------------- #

@pytest.mark.parametrize(
    "policy,code,expect",
    [
        (RestartPolicy.ALWAYS, 0, True),
        (RestartPolicy.ALWAYS, 1, True),
        (RestartPolicy.ON_FAILURE, 0, False),
        (RestartPolicy.ON_FAILURE, 1, True),
        (RestartPolicy.NEVER, 1, False),
        (RestartPolicy.EXIT_CODE, 1, False),      # app error: permanent
        (RestartPolicy.EXIT_CODE, 127, False),
        (RestartPolicy.EXIT_CODE, 137, True),     # SIGKILL: infra, retry
        (RestartPolicy.EXIT_CODE, 139, True),     # SIGSEGV
    ],
)
def test_restart_policy_table(policy, code, expect):
    assert policy.should_restart(code) is expect


def test_jobspec_validation():
    with pytest.raises(ValueError):
        JobSpec(name="x", replicas={})
    with pytest.raises(ValueError):
        JobSpec(name="x", replicas={"w": ReplicaSpec(replicas=0, command=("a",))})
    with pytest.raises(ValueError):
        JobSpec(name="x", replicas={"w": ReplicaSpec(replicas=1)})


def test_rank_ordering_master_first():
    job = JobSpec(
        name="j",
        replicas={
            "worker": ReplicaSpec(replicas=2, command=("w",)),
            "master": ReplicaSpec(replicas=1, command=("m",)),
        },
    )
    ranks = job.global_ranks()
    assert ranks[("master", 0)] == 0
    assert ranks[("worker", 0)] == 1
    assert ranks[("worker", 1)] == 2
    assert job.total_replicas == 3


def test_jobspec_dict_roundtrip():
    job = JobSpec(
        name="j",
        replicas={
            "worker": ReplicaSpec(
                replicas=2,
                command=(PY, "-c", "pass"),
                env={"A": "1"},
                restart_policy=RestartPolicy.EXIT_CODE,
                tpu=TPURequest(chips=4, topology="2x2"),
            )
        },
    )
    clone = JobSpec.from_dict(job.to_dict())
    assert clone.to_dict() == job.to_dict()
    assert clone.replicas["worker"].tpu.topology == "2x2"


def test_env_wiring():
    job = JobSpec(
        name="j",
        replicas={
            "master": ReplicaSpec(replicas=1, command=("m",), env={"USER_VAR": "u"}),
            "worker": ReplicaSpec(replicas=2, command=("w",)),
        },
    )
    env = envwire.build_worker_env(
        job, "worker", 1,
        coordinator_port=1234,
        wiring=envwire.WiringConfig(platform="cpu_sim", devices_per_worker=2),
        workdir="/tmp/w", attempt=3, base_env={"PALLAS_AXON_X": "1"},
    )
    assert env["JAX_COORDINATOR_ADDRESS"] == "127.0.0.1:1234"
    assert env["JAX_NUM_PROCESSES"] == "3"
    assert env["JAX_PROCESS_ID"] == "2"  # master=0, worker-0=1, worker-1=2
    assert env["KFT_REPLICA_TYPE"] == "worker"
    assert env["KFT_ATTEMPT"] == "3"
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]
    assert "PALLAS_AXON_X" not in env  # axon registration disabled in children
    master_env = envwire.build_worker_env(
        job, "master", 0, coordinator_port=1234,
        wiring=envwire.WiringConfig(), workdir="/tmp/w", attempt=0,
    )
    assert master_env["USER_VAR"] == "u"
    assert master_env["JAX_PROCESS_ID"] == "0"


# --------------------------- store ------------------------------------ #

def test_store_crud_and_watch():
    s = ObjectStore("t")
    s.create("a", {"v": 1})
    with pytest.raises(KeyError):
        s.create("a", {})
    watch = s.watch()
    ev = watch.poll(timeout=1)
    assert ev.kind == "ADDED" and ev.key == "a"  # replay of current state
    s.update("a", {"v": 2})
    assert watch.poll(timeout=1).kind == "MODIFIED"
    s.mutate("a", lambda o: o.update(v=3))
    assert s.get("a")["v"] == 3
    s.delete("a")
    ev = watch.poll(timeout=1)  # mutate event
    ev = watch.poll(timeout=1)  # delete event
    assert ev.kind == "DELETED"
    watch.stop()


# --------------------------- fleet ------------------------------------ #

def test_parse_topology():
    assert parse_topology("4x4") == (4, 4)
    assert topology_chips("2x4") == 8
    with pytest.raises(ValueError):
        parse_topology("4xx")


def test_fleet_gang_all_or_nothing():
    fleet = Fleet.homogeneous(2, "2x2")  # 2 slices x 4 chips
    assert fleet.total_chips() == 8
    # gang of 3x2 chips fits (4+2 on one slice, 2... best fit packs)
    claims = fleet.claim_gang([(2, None, "v5e")] * 3)
    assert claims is not None and fleet.free_chips() == 2
    # next gang of 2x2 chips: only 2 free → all-or-nothing refuses
    assert fleet.claim_gang([(2, None, "v5e")] * 2) is None
    assert fleet.free_chips() == 2  # nothing leaked
    fleet.release(claims)
    assert fleet.free_chips() == 8


def test_fleet_whole_slice_topology_claim():
    fleet = Fleet.homogeneous(2, "2x2")
    # partial claim dirties slice-0 (best-fit will pick one slice)
    partial = fleet.claim_gang([(1, None, "v5e")])
    # whole-slice claim must land on the untouched slice
    whole = fleet.claim_gang([(0, "2x2", "v5e")])
    assert whole is not None
    assert whole[0].slice_id != partial[0].slice_id
    assert whole[0].chips == 4
    # no second clean slice left
    assert fleet.claim_gang([(0, "2x2", "v5e")]) is None


def test_fleet_generation_mismatch():
    fleet = Fleet.homogeneous(1, "2x2", generation="v5e")
    assert fleet.claim_gang([(1, None, "v4")]) is None


def test_slice_loss_simulation():
    fleet = Fleet.homogeneous(2, "2x2")
    fleet.remove_slice("slice-0")
    assert fleet.total_chips() == 4


# --------------------------- gang scheduler ---------------------------- #

def _group(uid, n_chips, n_members=1, **kw):
    return PodGroup(
        job_uid=uid,
        requests=[(f"{uid}/w-{i}", n_chips, None, "v5e") for i in range(n_members)],
        **kw,
    )


def test_gang_priority_then_fifo():
    sched = GangScheduler(Fleet.homogeneous(1, "2x2"))
    sched.enqueue(_group("low", 4, priority=0))
    sched.enqueue(_group("high", 4, priority=5))
    admitted = sched.try_schedule()
    assert [g.job_uid for g in admitted] == ["high"]
    assert sched.claims_for("high") is not None
    assert sched.claims_for("low") is None
    sched.cancel("high")  # releases claims
    assert [g.job_uid for g in sched.try_schedule()] == ["low"]


def test_gang_head_of_line_blocks_queue():
    sched = GangScheduler(Fleet.homogeneous(1, "2x2"))
    sched.enqueue(_group("big", 4, n_members=2))   # needs 8, can't fit
    sched.enqueue(_group("small", 1))
    assert sched.try_schedule() == []  # small must NOT jump the blocked head
    # ...but a different queue is independent
    sched.enqueue(_group("other", 1, queue="q2"))
    assert [g.job_uid for g in sched.try_schedule()] == ["other"]


def test_gang_timeout():
    sched = GangScheduler(Fleet.homogeneous(1, "1x1"))
    sched.enqueue(_group("imposs", 99, timeout_seconds=0.0))
    assert sched.try_schedule() == []
    timed = sched.timed_out()
    assert [g.job_uid for g in timed] == ["imposs"]
    assert sched.pending_count() == 0


# ----------------------- failure-policy mechanics ---------------------- #


def test_fleet_slice_loss_visibility_and_release_tolerance():
    fleet = Fleet.homogeneous(2, "2x2")
    claims = fleet.claim_gang([(4, None, "v5e")])
    assert claims is not None
    sid = claims[0].slice_id
    assert fleet.has_slice(sid)
    fleet.remove_slice(sid)
    assert not fleet.has_slice(sid)
    # releasing claims against a lost slice must be a no-op, not a crash
    fleet.release(claims)
    assert fleet.free_chips() == 4  # only the surviving slice counts


def test_active_deadline_expiry_drives_failed_condition(tmp_path):
    """RunPolicy.activeDeadlineSeconds enforcement, driven directly
    through the reconciler with a fabricated start time — no wall-clock
    waiting on the deadline itself."""
    import time

    from kubeflow_tpu.orchestrator.cluster import LocalCluster
    from kubeflow_tpu.orchestrator.spec import RunPolicy, WorkerPhase

    cluster = LocalCluster(base_dir=str(tmp_path))  # NOT started: we sync
    job = JobSpec(
        name="deadline",
        replicas={
            "worker": ReplicaSpec(
                replicas=1,
                command=(PY, "-c", "import time; time.sleep(60)"),
                tpu=TPURequest(chips=1),
            )
        },
        run_policy=RunPolicy(active_deadline_seconds=5.0),
    )
    uid = cluster.submit(job)
    deadline = time.time() + 10
    while time.time() < deadline:
        cluster.controller.sync_all()
        st = cluster.status(uid)
        if st is not None and st.start_time is not None:
            break
        time.sleep(0.02)
    assert cluster.status(uid).start_time is not None

    # job "has been running" longer than the deadline: next sync fails it
    def _age(j):
        j.status.start_time = time.time() - 6.0

    cluster.jobs.mutate(uid, _age)
    cluster.controller.sync_job(uid)
    st = cluster.status(uid)
    assert st.phase == "Failed"
    assert st.condition().reason == "DeadlineExceeded"
    # cleanPodPolicy killed the sleeper
    deadline = time.time() + 10
    while time.time() < deadline:
        if not any(
            cluster.launcher.alive(k)
            for k, _ in cluster.workers.list(prefix=f"{uid}/")
        ):
            break
        time.sleep(0.02)
    for k, _w in cluster.workers.list(prefix=f"{uid}/"):
        assert not cluster.launcher.alive(k)
    cluster.launcher.shutdown()


def test_reconciler_requeues_gang_on_slice_loss(tmp_path):
    """The reconcile-level slice-loss contract, synchronously: lost
    placement ⇒ RESTARTING/SliceLost, claims released, workers reset to
    PENDING at attempt 1, and NO restart/backoff budget burned."""
    import time

    from kubeflow_tpu.orchestrator.cluster import LocalCluster
    from kubeflow_tpu.orchestrator.spec import (
        JobConditionType as CT, WorkerPhase,
    )

    cluster = LocalCluster(base_dir=str(tmp_path))
    job = JobSpec(
        name="lost-slice",
        replicas={
            "worker": ReplicaSpec(
                replicas=1,
                command=(PY, "-c", "import time; time.sleep(60)"),
                tpu=TPURequest(chips=1),
            )
        },
    )
    uid = cluster.submit(job)
    deadline = time.time() + 10
    while time.time() < deadline:
        cluster.controller.sync_all()
        ws = cluster.workers.list(prefix=f"{uid}/")
        if ws and all(w.phase is WorkerPhase.RUNNING for _, w in ws):
            break
        time.sleep(0.02)
    [(key, w)] = cluster.workers.list(prefix=f"{uid}/")
    assert w.slice_id is not None
    cluster.fleet.remove_slice(w.slice_id)

    cluster.controller.sync_job(uid)
    st = cluster.status(uid)
    restarting = [c for c in st.conditions if c.type is CT.RESTARTING]
    assert restarting and restarting[0].reason == "SliceLost"
    assert st.restart_count == 0  # infra loss burns no backoff budget
    [(key, w)] = cluster.workers.list(prefix=f"{uid}/")
    assert w.phase is WorkerPhase.PENDING
    assert w.restarts == 1 and w.slice_id is None
    assert cluster.scheduler.claims_for(uid) is None
    # no capacity left: the gang queues instead of failing
    cluster.controller.sync_job(uid)
    st = cluster.status(uid)
    assert any(c.type is CT.QUEUED and c.status for c in st.conditions)
    cluster.launcher.shutdown()
