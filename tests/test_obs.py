"""Observability plane: prom registry/exposition, heartbeats, profiler,
JSON logs, ObsServer endpoints (SURVEY.md §5.1/§5.5 equivalents)."""

import io
import json
import logging
import time
import urllib.request

import pytest

from kubeflow_tpu.obs import (
    HeartbeatWriter,
    JsonFormatter,
    ObsServer,
    Registry,
    capture_trace,
    heartbeat_path,
    is_stale,
    read_heartbeat,
)


# -- prom ----------------------------------------------------------------- #


def test_counter_and_gauge_exposition():
    reg = Registry()
    c = reg.counter("req_total", "requests", labels=("code",))
    c.labels(code="200").inc()
    c.labels(code="200").inc(2)
    c.labels(code="500").inc()
    g = reg.gauge("temp", "temperature")
    g.set(3.5)
    g.inc()
    text = reg.expose()
    assert "# TYPE req_total counter" in text
    assert 'req_total{code="200"} 3' in text
    assert 'req_total{code="500"} 1' in text
    assert "# TYPE temp gauge" in text
    assert "temp 4.5" in text


def test_counter_rejects_negative_and_wrong_labels():
    reg = Registry()
    c = reg.counter("x_total", "x", labels=("a",))
    with pytest.raises(ValueError):
        c.labels(a="1").inc(-1)
    with pytest.raises(ValueError):
        c.labels(b="1")
    with pytest.raises(ValueError):
        c.inc()  # labeled metric needs .labels()


def test_registry_rejects_type_conflicts_and_dedupes():
    reg = Registry()
    c1 = reg.counter("m", "m")
    c2 = reg.counter("m", "m")
    assert c1 is c2
    with pytest.raises(ValueError):
        reg.gauge("m", "m")
    h1 = reg.histogram("h", "h", buckets=(1.0, 2.0))
    assert reg.histogram("h", "h", buckets=(1.0, 2.0)) is h1
    with pytest.raises(ValueError):  # silent bucket drift is a data bug
        reg.histogram("h", "h", buckets=(5.0,))


def test_histogram_buckets_cumulative():
    reg = Registry()
    h = reg.histogram("lat", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    text = reg.expose()
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1"} 3' in text
    assert 'lat_bucket{le="10"} 4' in text
    assert 'lat_bucket{le="+Inf"} 5' in text
    assert "lat_count 5" in text
    assert "lat_sum 56.05" in text


def test_histogram_timer():
    reg = Registry()
    h = reg.histogram("t", "t", buckets=(10.0,))
    with h.time():
        pass
    assert "t_count 1" in reg.expose()


# -- heartbeat ------------------------------------------------------------ #


def test_heartbeat_roundtrip_and_staleness(tmp_path):
    path = heartbeat_path(tmp_path, "worker", 0)
    hb = HeartbeatWriter(path, interval=0.05, attempt=2)
    with hb:
        hb.beat(step=7)
        beat = read_heartbeat(path)
        assert beat is not None
        assert beat.step == 7
        assert beat.attempt == 2
        assert not is_stale(path, timeout=5.0)
        # beats from an older attempt don't count for the current one
        assert not is_stale(path, timeout=0.0, min_attempt=3)
    time.sleep(0.15)
    assert is_stale(path, timeout=0.1)  # writer stopped → goes stale


def test_heartbeat_background_thread_beats(tmp_path):
    path = heartbeat_path(tmp_path, "worker", 1)
    with HeartbeatWriter(path, interval=0.02):
        time.sleep(0.1)
        first = read_heartbeat(path).time
        time.sleep(0.1)
        assert read_heartbeat(path).time > first


def test_missing_heartbeat_is_not_stale(tmp_path):
    assert not is_stale(tmp_path / "nope.json", timeout=0.0)
    assert read_heartbeat(tmp_path / "nope.json") is None


def test_heartbeat_from_env(tmp_path, monkeypatch):
    from kubeflow_tpu.orchestrator import envwire

    monkeypatch.setenv(envwire.ENV_WORKDIR, str(tmp_path))
    monkeypatch.setenv(envwire.ENV_REPLICA_TYPE, "worker")
    monkeypatch.setenv(envwire.ENV_REPLICA_INDEX, "3")
    monkeypatch.setenv(envwire.ENV_ATTEMPT, "1")
    hb = HeartbeatWriter.from_env()
    assert hb is not None
    hb.beat()
    beat = read_heartbeat(heartbeat_path(tmp_path, "worker", 3))
    assert beat.attempt == 1


def test_heartbeat_published_step_never_regresses(tmp_path):
    """Race regression (kft lint lock-discipline work): beat(step=N) from
    the metric drain races the background beat() thread. Before the fix,
    the payload was built OUTSIDE the write lock, so the background thread
    could snapshot step N-1, lose the race, and publish it AFTER the drain
    published N — observed trainer progress (chaos triggers, supervisor
    progress clocks) would regress. With step update + payload build +
    publish in one critical section, the file's step is monotonic."""
    path = heartbeat_path(tmp_path, "worker", 0)
    # interval=0: the background thread republishes as fast as it can,
    # maximizing interleavings with the explicit stepped beats
    with HeartbeatWriter(path, interval=0.0) as hb:
        seen = -1
        for step in range(300):
            hb.beat(step=step)
            beat = read_heartbeat(path)
            if beat is not None:  # None = mid-replace read, fine
                assert beat.step >= seen, (
                    f"published step regressed: {beat.step} after {seen}"
                )
                seen = max(seen, beat.step)
        assert seen >= 0


def test_heartbeat_age_uses_monotonic_clock(tmp_path):
    """Staleness is duration math on time.monotonic(): a wall-clock jump
    must not age a beat. The stamp must compare against monotonic 'now',
    not time.time() (which differs from monotonic by decades)."""
    path = heartbeat_path(tmp_path, "worker", 0)
    hb = HeartbeatWriter(path)
    hb.beat(step=1)
    beat = read_heartbeat(path)
    assert abs(beat.age()) < 5.0  # same clock domain as the stamp
    assert not is_stale(path, timeout=5.0)
    # an explicitly monotonic 'now' also reads fresh
    assert not is_stale(path, timeout=5.0, now=time.monotonic())


# -- json logging --------------------------------------------------------- #


def test_json_formatter_fields():
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    handler.setFormatter(JsonFormatter(static_fields={"svc": "test"}))
    log = logging.getLogger("kft.test.json")
    log.addHandler(handler)
    log.setLevel(logging.INFO)
    try:
        log.info("hello %s", "world", extra={"fields": {"k": 1}})
        try:
            raise ValueError("boom")
        except ValueError:
            log.exception("failed")
    finally:
        log.removeHandler(handler)
    lines = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert lines[0]["msg"] == "hello world"
    assert lines[0]["svc"] == "test"
    assert lines[0]["k"] == 1
    assert lines[0]["level"] == "info"
    assert "ValueError: boom" in lines[1]["exc"]


# -- profiler + server ---------------------------------------------------- #


def test_capture_trace_writes_events(tmp_path):
    import jax
    import jax.numpy as jnp

    logdir = tmp_path / "prof"
    with capture_trace(logdir):
        jax.block_until_ready(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
    files = list(logdir.rglob("*"))
    assert any(f.suffix in (".pb", ".gz", ".json") or "trace" in f.name
               for f in files if f.is_file()), files


def test_obs_server_endpoints(tmp_path):
    reg = Registry()
    reg.counter("up", "up").inc()
    with ObsServer(
        registry=reg,
        profile_logdir=tmp_path,
        state_fn=lambda: {"jobs": 2},
    ) as srv:
        assert urllib.request.urlopen(srv.url + "/healthz").read() == b"ok"
        metrics = urllib.request.urlopen(srv.url + "/metrics").read().decode()
        assert "up 1" in metrics
        state = json.loads(
            urllib.request.urlopen(srv.url + "/debug/state").read()
        )
        assert state == {"jobs": 2}
        req = urllib.request.Request(
            srv.url + "/profile?seconds=0.1", method="POST"
        )
        out = json.loads(urllib.request.urlopen(req).read())
        assert out["seconds"] == 0.1
        import pathlib

        assert pathlib.Path(out["logdir"]).exists()
