"""Elasticity + failure detection (SURVEY.md §5.3, §7 step 8).

Three layers, mirroring the reference's test strategy (§4):
- control-plane units: ElasticPolicy clamping, scale() state machine,
  heartbeat-supervisor kills — trivial non-JAX payloads, fast;
- fault injection e2e: SIGKILL a worker mid-MNIST-training, assert the gang
  restarts and RESUMES from the Orbax checkpoint (not from step 0);
- elastic-restart e2e: scale a 2-worker job down to 1 mid-run, assert
  training resumes from checkpoint onto the reshaped (smaller) mesh.
"""

import sys
import time
from pathlib import Path

import pytest

from kubeflow_tpu.obs import heartbeat as hb
from kubeflow_tpu.orchestrator import (
    ElasticPolicy,
    JobSpec,
    ReplicaSpec,
    RestartPolicy,
    TPURequest,
    LocalCluster,
)
from kubeflow_tpu.orchestrator.envwire import WiringConfig
from kubeflow_tpu.orchestrator.resources import Fleet
from kubeflow_tpu.orchestrator.spec import JobConditionType as CT
from kubeflow_tpu.train.metrics import parse_stdout_metrics

REPO = str(Path(__file__).resolve().parent.parent)
PY = sys.executable

#: hand-writes the heartbeat file per the documented JSON protocol (no
#: framework import → child starts in milliseconds); beats once, then hangs
#: beat-less on attempt 0 and exits clean on later attempts.
HANG_THEN_OK = """
import json, os, sys, time
workdir = os.environ["KFT_WORKDIR"]
rtype = os.environ["KFT_REPLICA_TYPE"]
index = os.environ["KFT_REPLICA_INDEX"]
attempt = int(os.environ["KFT_ATTEMPT"])
path = os.path.join(workdir, f"heartbeat-{rtype}-{index}.json")
def beat():
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"time": time.monotonic(), "pid": os.getpid(),
                   "step": -1, "attempt": attempt}, f)
    os.replace(tmp, path)
beat()
if attempt == 0:
    time.sleep(120)   # wedged: alive but never beats again
else:
    beat()
    sys.exit(0)
"""


@pytest.fixture()
def cluster(tmp_path):
    c = LocalCluster(
        fleet=Fleet.homogeneous(2, "2x2"),
        base_dir=str(tmp_path),
        restart_backoff_base=0.05,
        resync_period=0.05,
    )
    with c:
        yield c


# -- control-plane units -------------------------------------------------- #


def test_elastic_policy_clamp():
    p = ElasticPolicy(min_replicas=2, max_replicas=4)
    assert p.clamp(1) == 2
    assert p.clamp(3) == 3
    assert p.clamp(9) == 4
    assert ElasticPolicy(min_replicas=1).clamp(7) == 7  # unbounded above


def test_elastic_policy_rejects_inverted_bounds():
    with pytest.raises(ValueError, match="min_replicas"):
        ElasticPolicy(min_replicas=4, max_replicas=2)


def test_spec_rejects_unknown_elastic_group():
    with pytest.raises(ValueError, match="elastic.replica_type"):
        JobSpec(
            name="bad",
            replicas={"worker": ReplicaSpec(command=("true",))},
            elastic=ElasticPolicy(replica_type="trainer"),
        )


def test_scale_requires_elastic_policy(cluster):
    spec = JobSpec(
        name="static",
        replicas={
            "worker": ReplicaSpec(
                replicas=1, command=(PY, "-c", "import time; time.sleep(60)")
            )
        },
    )
    uid = cluster.submit(spec)
    deadline = time.time() + 30
    while time.time() < deadline and cluster.status(uid).phase != "Running":
        time.sleep(0.05)
    with pytest.raises(ValueError, match="no elastic policy"):
        cluster.scale(uid, 2)
    cluster.delete(uid)


def test_scale_reforms_gang_at_new_size(cluster):
    spec = JobSpec(
        name="elastic-sleep",
        replicas={
            "worker": ReplicaSpec(
                replicas=2,
                command=(PY, "-c", "import time; time.sleep(60)"),
                tpu=TPURequest(chips=1),
            )
        },
        elastic=ElasticPolicy(min_replicas=1, max_replicas=3),
    )
    uid = cluster.submit(spec)
    deadline = time.time() + 30
    while time.time() < deadline and cluster.status(uid).phase != "Running":
        time.sleep(0.05)
    assert cluster.status(uid).phase == "Running"

    assert cluster.scale(uid, 5) == 3  # clamped to max
    job = cluster.get(uid)
    restarting = [c for c in job.status.conditions if c.type is CT.RESTARTING]
    assert restarting and restarting[0].reason == "Scaled"

    deadline = time.time() + 30
    while time.time() < deadline:
        ws = list(cluster.workers.list(prefix=f"{uid}/"))
        if len(ws) == 3 and all(
            w.phase.value == "Running" for _, w in ws
        ):
            break
        time.sleep(0.05)
    ws = list(cluster.workers.list(prefix=f"{uid}/"))
    assert len(ws) == 3
    job = cluster.get(uid)
    # the reconcile loop applied the new size to the spec...
    assert job.spec.replicas["worker"].replicas == 3
    # ...and scaling never burns failure-backoff budget
    assert job.status.restart_count == 0
    assert cluster.scale(uid, 3) == 3  # no-op resize is accepted
    cluster.delete(uid)


def test_supervisor_kills_hung_worker_and_gang_recovers(cluster):
    spec = JobSpec(
        name="hung",
        replicas={
            "worker": ReplicaSpec(
                replicas=2,
                command=(PY, "-c", HANG_THEN_OK),
                restart_policy=RestartPolicy.ON_FAILURE,
                tpu=TPURequest(chips=1),
            )
        },
        elastic=ElasticPolicy(
            heartbeat_timeout_seconds=0.4, heartbeat_grace_seconds=10.0
        ),
    )
    uid = cluster.submit(spec)
    status = cluster.wait(uid, timeout=60)
    assert status.phase == "Succeeded", [
        c.to_dict() for c in status.conditions
    ]
    # both workers hung on attempt 0 → supervisor killed them (137) →
    # one gang restart → attempt 1 exits 0
    assert status.restart_count == 1


#: beats CONTINUOUSLY on a thread (like a live HeartbeatWriter) but never
#: advances the step on attempt 0 — the wedged-main-thread signature.
BEAT_BUT_STUCK = HANG_THEN_OK.replace(
    'if attempt == 0:\n    time.sleep(120)',
    '''if attempt == 0:
    import threading
    def pump():
        while True:
            beat(); time.sleep(0.05)
    threading.Thread(target=pump, daemon=True).start()
    time.sleep(120)''',
)


def test_supervisor_kills_on_progress_stall(cluster):
    spec = JobSpec(
        name="stuck-step",
        replicas={
            "worker": ReplicaSpec(
                replicas=1,
                command=(PY, "-c", BEAT_BUT_STUCK),
                restart_policy=RestartPolicy.ON_FAILURE,
            )
        },
        elastic=ElasticPolicy(
            # beats stay fresh — only the progress watchdog can catch this
            heartbeat_timeout_seconds=30.0,
            heartbeat_grace_seconds=30.0,
            progress_timeout_seconds=0.6,
        ),
    )
    uid = cluster.submit(spec)
    status = cluster.wait(uid, timeout=60)
    assert status.phase == "Succeeded", [c.to_dict() for c in status.conditions]
    assert status.restart_count == 1


def test_supervisor_ignores_non_elastic_groups(cluster):
    """A master that never beats must not be executed for silence — only
    the elastic replica_type group is expected to heartbeat."""
    spec = JobSpec(
        name="quiet-master",
        replicas={
            "master": ReplicaSpec(
                replicas=1, command=(PY, "-c", "import time; time.sleep(1.0)")
            ),
            "worker": ReplicaSpec(
                replicas=1, command=(PY, "-c", HANG_THEN_OK),
                restart_policy=RestartPolicy.ON_FAILURE,
            ),
        },
        elastic=ElasticPolicy(
            replica_type="worker",
            heartbeat_timeout_seconds=0.4,
            heartbeat_grace_seconds=0.1,  # would kill the master instantly
        ),
    )
    uid = cluster.submit(spec)
    status = cluster.wait(uid, timeout=60)
    # Success proves the master was never kill-looped to BackoffLimit;
    # restart_count proves the hung worker WAS caught.
    assert status.phase == "Succeeded", [c.to_dict() for c in status.conditions]
    assert status.restart_count >= 1


def test_supervisor_covers_explicitly_supervised_master(cluster):
    """With supervised_replica_types including the master, a master that
    never beats is killed at grace expiry — the PyTorchJob-style case where
    the coordinator is itself a trainer."""
    spec = JobSpec(
        name="watched-master",
        replicas={
            "master": ReplicaSpec(
                replicas=1,
                command=(PY, "-c", HANG_THEN_OK),
                restart_policy=RestartPolicy.ON_FAILURE,
            ),
            "worker": ReplicaSpec(
                replicas=1, command=(PY, "-c", HANG_THEN_OK),
                restart_policy=RestartPolicy.ON_FAILURE,
            ),
        },
        elastic=ElasticPolicy(
            replica_type="worker",
            supervised_replica_types=("master", "worker"),
            heartbeat_timeout_seconds=0.4,
        ),
    )
    uid = cluster.submit(spec)
    status = cluster.wait(uid, timeout=60)
    assert status.phase == "Succeeded", [c.to_dict() for c in status.conditions]
    assert status.restart_count == 1


def test_supervisor_respects_startup_grace(cluster, tmp_path):
    sup = cluster.supervisor
    spec = JobSpec(
        name="graceful",
        replicas={
            "worker": ReplicaSpec(
                replicas=1,
                # beats nothing at all, exits after 1.2s
                command=(PY, "-c", "import time; time.sleep(1.2)"),
            )
        },
        elastic=ElasticPolicy(
            heartbeat_timeout_seconds=0.2, heartbeat_grace_seconds=30.0
        ),
    )
    uid = cluster.submit(spec)
    status = cluster.wait(uid, timeout=30)
    # never killed: no beat ever arrived, but grace covered the lifetime
    assert status.phase == "Succeeded"
    assert status.restart_count == 0
    assert sup.check() == []


# -- data-plane e2e: fault injection + elastic restart -------------------- #


def _mnist_job(tmp_path, *, replicas, steps, elastic=None, name="mnist"):
    return JobSpec(
        name=name,
        replicas={
            "worker": ReplicaSpec(
                replicas=replicas,
                command=(
                    PY, "-m", "kubeflow_tpu.examples.mnist",
                    "--steps", str(steps), "--global-batch", "32",
                    "--log-every", "1", "--lr", "3e-3",
                    "--checkpoint-dir", str(tmp_path / "ckpt"),
                    "--checkpoint-every", "2",
                ),
                env={"PYTHONPATH": REPO},
                restart_policy=RestartPolicy.ON_FAILURE,
                tpu=TPURequest(chips=4),
            )
        },
        elastic=elastic,
    )


from conftest import wait_for_job_step as _wait_for_step  # noqa: E402


@pytest.mark.slow
def test_sigkill_worker_resumes_from_checkpoint(tmp_path):
    cluster = LocalCluster(
        fleet=Fleet.homogeneous(2, "2x2"),
        wiring=WiringConfig(platform="cpu_sim", devices_per_worker=4),
        base_dir=str(tmp_path),
        restart_backoff_base=0.05,
        resync_period=0.05,
    )
    with cluster:
        uid = cluster.submit(_mnist_job(tmp_path, replicas=2, steps=10))
        _wait_for_step(cluster, uid, 3)  # ≥1 checkpoint (every 2) durable
        assert cluster.launcher.kill(f"{uid}/worker-1")  # the chaos event

        status = cluster.wait(uid, timeout=600)
        log0_all = cluster.logs(uid, "worker", 0)
        assert status.phase == "Succeeded", f"log:\n{log0_all}"
        assert status.restart_count == 1

        # Attempt 1 must RESUME: its first logged step is after the restored
        # checkpoint (>2 would also catch an off-by-one replay; >1 proves
        # it did not start over).
        log0_retry = cluster.logs(uid, "worker", 0, attempt=1)
        retry_steps = [m["step"] for m in parse_stdout_metrics(log0_retry)]
        assert retry_steps, f"no metrics in attempt-1 log:\n{log0_retry}"
        assert retry_steps[0] > 1, retry_steps
        assert retry_steps[-1] == 10
        assert "final_loss=" in log0_retry


@pytest.mark.slow
def test_scale_down_resumes_on_smaller_mesh(tmp_path):
    cluster = LocalCluster(
        fleet=Fleet.homogeneous(2, "2x2"),
        wiring=WiringConfig(platform="cpu_sim", devices_per_worker=4),
        base_dir=str(tmp_path),
        restart_backoff_base=0.05,
        resync_period=0.05,
    )
    with cluster:
        uid = cluster.submit(
            _mnist_job(
                tmp_path, replicas=2, steps=10,
                elastic=ElasticPolicy(min_replicas=1, max_replicas=2),
                name="mnist-elastic",
            )
        )
        _wait_for_step(cluster, uid, 3)
        assert cluster.scale(uid, 1) == 1

        status = cluster.wait(uid, timeout=600)
        log0 = cluster.logs(uid, "worker", 0)
        assert status.phase == "Succeeded", f"log:\n{log0}"
        assert cluster.get(uid).spec.replicas["worker"].replicas == 1

        # world was 2x4=8 devices before the scale, 4 after — and the
        # post-scale run resumed from checkpoint rather than replaying 0.
        assert "4 local / 8 global" in log0
        assert "4 local / 4 global" in log0
        post = log0.split("4 local / 4 global", 1)[1]
        post_steps = [m["step"] for m in parse_stdout_metrics(post)]
        assert post_steps and post_steps[0] > 1, post_steps
        assert post_steps[-1] == 10
