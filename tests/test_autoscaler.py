"""HPA-analog elastic autoscaling (orchestrator/autoscaler.py): the
recommendation formula + stabilization machinery unit-tested against a
fake cluster, then a REAL elastic job scaled down and back up through
checkpoint-restart by injected metrics (SURVEY.md §2.1 elastic row)."""

from __future__ import annotations

import os
import sys
import time
from types import SimpleNamespace

import pytest

from kubeflow_tpu.orchestrator.autoscaler import (
    AutoscalePolicy,
    ElasticAutoscaler,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


# ------------------------------------------------------------------ formula


def test_policy_formulas_and_deadband():
    # utilization (K8s formula): per-replica load vs target
    p = AutoscalePolicy(target=10.0, mode="utilization", min_replicas=1,
                        max_replicas=8)
    assert p.desired(2, 20.0) == 4          # ceil(2 * 20/10)
    assert p.desired(4, 5.0) == 2           # shrink proportionally
    assert p.desired(3, 10.5) == 3          # within 10% tolerance: hold
    assert p.desired(2, 200.0) == 8         # clamped to max
    assert p.desired(4, 0.0) == 4           # no signal != scale to zero

    # rate_floor: aggregate rate kept >= target
    r = AutoscalePolicy(target=8.0, mode="rate_floor", min_replicas=1,
                        max_replicas=4)
    assert r.desired(2, 4.0) == 4           # at half the SLO: double
    assert r.desired(2, 8.4) == 2           # within tolerance: hold
    assert r.desired(2, 32.0) == 1          # 4x headroom: shrink (clamped)

    with pytest.raises(ValueError, match="mode"):
        AutoscalePolicy(target=1.0, mode="nope")
    with pytest.raises(ValueError, match="target"):
        AutoscalePolicy(target=0.0)
    with pytest.raises(ValueError, match="min"):
        AutoscalePolicy(target=1.0, min_replicas=5, max_replicas=2)


# -------------------------------------------------------------- fake cluster


class _FakeCluster:
    def __init__(self, replicas=2):
        self._replicas = replicas
        self.finished = False
        self.scales: list[int] = []

    def status(self, uid):
        return SimpleNamespace(finished=self.finished)

    def get(self, uid):
        return SimpleNamespace(
            spec=SimpleNamespace(
                replicas={"worker": SimpleNamespace(replicas=self._replicas)}
            )
        )

    def scale(self, uid, n):
        self.scales.append(n)
        self._replicas = n
        return n

    def logs(self, uid, group, index):  # pragma: no cover - default scrape
        return ""


def _scaler(cluster, values, **pol_kw):
    """Autoscaler whose metric_fn pops from a value sequence."""
    seq = list(values)
    pol = AutoscalePolicy(**pol_kw)
    a = ElasticAutoscaler(
        cluster, metric_fn=lambda uid, p: seq.pop(0) if seq else None
    )
    a.register("j", pol)
    return a


def test_scale_up_is_immediate_and_cooldown_gates_next():
    c = _FakeCluster(replicas=2)
    a = _scaler(c, [4.0, 2.0, 2.0], target=8.0, mode="rate_floor",
                max_replicas=8, cooldown_s=10.0)
    assert a.tick(now=0.0) == {"j": 4}          # up right away
    assert a.tick(now=5.0) == {}                # cooldown holds
    assert a.tick(now=11.0) == {"j": 8}         # next resize after cooldown
    assert c.scales == [4, 8]


def test_scale_down_requires_stabilization_window():
    c = _FakeCluster(replicas=4)
    a = _scaler(c, [32.0, 32.0, 9.0, 32.0, 32.0], target=8.0,
                mode="rate_floor", max_replicas=8,
                scale_down_stabilization_s=30.0, cooldown_s=0.0)
    assert a.tick(now=0.0) == {}     # shrink recommended, held
    assert a.tick(now=10.0) == {}    # still inside the window
    assert a.tick(now=20.0) == {}    # recommendation back to hold → clears
    assert a.tick(now=25.0) == {}    # new shrink: clock restarts
    assert a.tick(now=60.0) == {"j": 1}  # held the full window → applied
    assert c.scales == [1]


def test_scale_down_applies_most_conservative_recommendation():
    """K8s HPA stabilization semantics: what gets applied after the
    window is the LARGEST (most conservative) shrink recommendation seen
    during it — a transient dip must not cause a deeper shrink."""
    c = _FakeCluster(replicas=4)
    # 32 → recommend 1 (deep, transient); 12 → recommend 3 (standing)
    a = _scaler(c, [32.0, 12.0, 12.0], target=8.0, mode="rate_floor",
                max_replicas=8, scale_down_stabilization_s=10.0,
                cooldown_s=0.0)
    assert a.tick(now=0.0) == {}
    assert a.tick(now=5.0) == {}
    assert a.tick(now=11.0) == {"j": 3}   # NOT the transient 1
    assert c.scales == [3]


def test_gone_job_unregisters_instead_of_starving_others():
    """LocalCluster returns None for TTL'd uids — the dead job must drop
    out and the healthy one keep autoscaling."""

    class _GoneCluster(_FakeCluster):
        def status(self, uid):
            return None if uid == "gone" else super().status(uid)

        def get(self, uid):
            return None if uid == "gone" else super().get(uid)

    c = _GoneCluster(replicas=2)
    a = ElasticAutoscaler(c, metric_fn=lambda u, p: 4.0)
    a.register("gone", AutoscalePolicy(target=8.0, mode="rate_floor"))
    a.register("live", AutoscalePolicy(target=8.0, mode="rate_floor",
                                       max_replicas=8))
    assert a.tick(now=0.0) == {"live": 4}
    assert "gone" not in a._jobs


def test_no_signal_is_a_noop_and_finished_unregisters():
    c = _FakeCluster(replicas=2)
    a = _scaler(c, [], target=8.0)   # metric_fn returns None forever
    assert a.tick(now=0.0) == {}
    assert c.scales == []
    c.finished = True
    a.tick(now=1.0)
    assert "j" not in a._jobs        # self-unregistered


# ------------------------------------------------------------------- e2e


@pytest.mark.slow
def test_autoscaler_resizes_real_job_through_checkpoint(tmp_path):
    """The VERDICT bar: a running elastic job scaled DOWN and back UP by
    the autoscaler, resuming from checkpoint across both resizes."""
    from kubeflow_tpu.orchestrator.cluster import LocalCluster
    from kubeflow_tpu.orchestrator.envwire import WiringConfig
    from kubeflow_tpu.orchestrator.resources import Fleet
    from kubeflow_tpu.orchestrator.spec import (
        ElasticPolicy,
        JobSpec,
        ReplicaSpec,
        RestartPolicy,
        TPURequest,
    )
    from kubeflow_tpu.train.metrics import parse_stdout_metrics

    from conftest import wait_for_job_step as wait_for_step

    cluster = LocalCluster(
        fleet=Fleet.homogeneous(2, "2x2"),
        wiring=WiringConfig(platform="cpu_sim", devices_per_worker=4),
        base_dir=str(tmp_path),
        restart_backoff_base=0.05,
        resync_period=0.05,
    )
    with cluster:
        uid = cluster.submit(JobSpec(
            name="mnist-autoscaled",
            replicas={"worker": ReplicaSpec(
                replicas=2,
                command=(
                    PY, "-m", "kubeflow_tpu.examples.mnist",
                    "--steps", "14", "--global-batch", "32",
                    "--log-every", "1", "--lr", "3e-3",
                    "--checkpoint-dir", str(tmp_path / "ckpt"),
                    "--checkpoint-every", "2",
                ),
                env={"PYTHONPATH": REPO},
                restart_policy=RestartPolicy.ON_FAILURE,
                tpu=TPURequest(chips=4),
            )},
            elastic=ElasticPolicy(min_replicas=1, max_replicas=2),
        ))
        # injected metric: the SLO story a real deployment would see —
        # far OVER target first (shrink), then far UNDER (grow back)
        phase = {"v": 20.0}
        scaler = ElasticAutoscaler(
            cluster, metric_fn=lambda u, p: phase["v"]
        )
        scaler.register(uid, AutoscalePolicy(
            target=2.0, metric="steps_per_sec", mode="rate_floor",
            min_replicas=1, max_replicas=2,
            scale_down_stabilization_s=0.2, cooldown_s=0.0,
        ))

        wait_for_step(cluster, uid, 3)  # a checkpoint (every 2) is durable
        assert scaler.tick(now=0.0) == {}            # shrink held...
        assert scaler.tick(now=1.0) == {uid: 1}      # ...then applied
        wait_for_step(cluster, uid, 6)
        phase["v"] = 0.2                              # now way under SLO
        assert scaler.tick(now=2.0) == {uid: 2}      # grow back, immediate

        status = cluster.wait(uid, timeout=600)
        log0 = cluster.logs(uid, "worker", 0)
        assert status.phase == "Succeeded", f"log:\n{log0}"
        assert cluster.get(uid).spec.replicas["worker"].replicas == 2
        # both world sizes really ran
        assert "4 local / 8 global" in log0
        assert "4 local / 4 global" in log0
        # after the LAST resize the job resumed from checkpoint, not step 0
        tail = log0.rsplit("4 local / 8 global", 1)[1]
        steps = [m["step"] for m in parse_stdout_metrics(tail)]
        assert steps and steps[0] > 1, steps
        assert steps[-1] == 14
        assert [e["to"] for e in scaler.events] == [1, 2]
        # the job finished → the next tick forgets it
        scaler.tick(now=3.0)
        assert uid not in scaler._jobs
