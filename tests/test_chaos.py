"""Chaos harness: inject every failure we claim to survive — and survive it.

Tier-1 by design (deterministic seeds, step-keyed triggers, no long sleeps):

- FaultPlan declarative surface (roundtrip, validation);
- kill-mid-train e2e: a FaultPlan SIGKILLs a worker at an observed trainer
  step; the gang restarts and resumes from the latest valid checkpoint at
  the exact next step — no repeated, no skipped steps;
- corrupt-latest checkpoint: restore detects the sha256-manifest mismatch
  and falls back to the previous step instead of dying or loading garbage;
- preemption: SIGTERM mid-fit → final checkpoint → exit code 143
  (retryable under RestartPolicy.EXIT_CODE) → exact-step resume;
- slice loss: the reconciler requeues the gang (no backoff burned) until
  capacity returns;
- wedge: SIGSTOP freezes a worker without exiting; the heartbeat
  supervisor detects and the gang recovers;
- storage fault injection through the fetcher-registry seam (retries,
  corruption rejection);
- `kft chaos run` CLI.
"""

import re
import signal
import sys
import time
from pathlib import Path

import pytest

from kubeflow_tpu.chaos import (
    ChaosRunner,
    CorruptCheckpoint,
    CrashWorker,
    DropSlice,
    FaultPlan,
    PreemptWorker,
    WedgeWorker,
    corrupt_checkpoint,
    storage_faults,
)
from kubeflow_tpu.obs.prom import REGISTRY
from kubeflow_tpu.orchestrator import (
    ElasticPolicy,
    JobSpec,
    LocalCluster,
    ReplicaSpec,
    RestartPolicy,
    TPURequest,
)
from kubeflow_tpu.orchestrator.envwire import WiringConfig
from kubeflow_tpu.orchestrator.resources import Fleet, Slice
from kubeflow_tpu.orchestrator.spec import JobConditionType as CT, WorkerPhase
from kubeflow_tpu.train.metrics import parse_stdout_metrics

REPO = str(Path(__file__).resolve().parent.parent)
PY = sys.executable

pytestmark = pytest.mark.chaos


def _counter_value(name: str, **labels) -> float:
    metric = REGISTRY._metrics.get(name)
    if metric is None:
        return 0.0
    child = metric._children.get(tuple(sorted(labels.items())))
    return child.value if child is not None else 0.0


# --------------------------------------------------------------------- #
# plan surface
# --------------------------------------------------------------------- #


def test_faultplan_roundtrip_and_validation():
    plan = FaultPlan(
        faults=(
            CrashWorker(at_step=3, index=1, sig=9),
            PreemptWorker(at_step=5, index=None, grace_s=2.0),
            WedgeWorker(),
            DropSlice(slice_id="slice-0"),
            CorruptCheckpoint(directory="/tmp/c", at_step=4),
        ),
        seed=42,
    )
    again = FaultPlan.from_dict(plan.to_dict())
    assert again == plan
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.from_dict({"faults": [{"kind": "Meteor"}]})
    with pytest.raises(TypeError):
        FaultPlan(faults=("not a fault",))


# --------------------------------------------------------------------- #
# the acceptance e2e: kill mid-train, resume at the exact next step
# --------------------------------------------------------------------- #


def test_chaos_kill_mid_train_resumes_exact_next_step(tmp_path):
    """FaultPlan SIGKILLs worker-0 once the trainer's heartbeat shows step
    >= 3. ExitCode policy restarts the gang; attempt 1 must restore the
    newest durable checkpoint (sync saves every step ⇒ step >= 3) and
    log exactly resume_step+1 .. steps: nothing repeated, nothing skipped,
    loss stream continuous across the crash."""
    cluster = LocalCluster(
        fleet=Fleet.homogeneous(1, "2x2"),
        wiring=WiringConfig(platform="cpu_sim", devices_per_worker=2),
        base_dir=str(tmp_path),
        restart_backoff_base=0.05,
        resync_period=0.05,
    )
    injected0 = _counter_value("kft_chaos_injected_total", kind="crash_worker")
    with cluster:
        job = JobSpec(
            name="chaos-mnist",
            replicas={
                "worker": ReplicaSpec(
                    replicas=1,
                    command=(
                        PY, "-m", "kubeflow_tpu.examples.mnist",
                        "--steps", "8", "--global-batch", "16",
                        "--log-every", "1",
                        "--checkpoint-dir", str(tmp_path / "ckpt"),
                        "--checkpoint-every", "1", "--checkpoint-sync",
                    ),
                    env={"PYTHONPATH": REPO},
                    restart_policy=RestartPolicy.EXIT_CODE,
                    tpu=TPURequest(chips=2),
                )
            },
        )
        uid = cluster.submit(job)
        plan = FaultPlan(
            faults=(CrashWorker(at_step=3, index=0, sig=9),), seed=1
        )
        report = ChaosRunner(cluster, uid, plan).drive(timeout=240)

        log_all = cluster.logs(uid, "worker", 0)
        assert report["phase"] == "Succeeded", f"log:\n{log_all}"
        assert report["restart_count"] == 1
        assert not report["pending"]
        [fired] = report["fired"]
        assert fired["fault"]["kind"] == "CrashWorker"
        assert fired["at_observed_step"] >= 3
        assert fired["recovered_after_s"] is not None

        # exact-step resume: attempt 1 declares where it restored from,
        # and its logged steps are precisely the continuation
        log1 = cluster.logs(uid, "worker", 0, attempt=1)
        m = re.search(r"resume_step=(\d+)", log1)
        assert m, f"no resume marker in attempt-1 log:\n{log1}"
        resume_step = int(m.group(1))
        assert resume_step >= 3  # sync save every step: nothing older
        steps1 = [int(x["step"]) for x in parse_stdout_metrics(log1)]
        assert steps1 == list(range(resume_step + 1, 9)), steps1
        # loss continuity: the resumed stream is real training, not a
        # restart from scratch (which would re-log step 1)
        losses1 = [x["loss"] for x in parse_stdout_metrics(log1)]
        assert losses1 and all(v == v for v in losses1)  # finite stream
        # nothing attempt 0 logged lies past the restore point: a logged
        # step implies its sync save was already durable (loop order), so
        # the restored step can never skip logged progress
        steps0 = [int(x["step"]) for x in parse_stdout_metrics(
            cluster.logs(uid, "worker", 0, attempt=0)
        )]
        assert steps0 and max(steps0) <= resume_step, (steps0, resume_step)

    # recovery observability landed on the shared registry
    assert _counter_value(
        "kft_chaos_injected_total", kind="crash_worker"
    ) == injected0 + 1
    assert "kft_recovery_seconds" in REGISTRY.expose()


# --------------------------------------------------------------------- #
# corrupt latest checkpoint → manifest-verified fallback
# --------------------------------------------------------------------- #


def _mnist_trainer(steps, ckpt_dir, **cfg_kw):
    import optax

    from kubeflow_tpu.core.mesh import MeshSpec
    from kubeflow_tpu.models.mnist_cnn import (
        MnistCNN, make_init_fn, make_loss_fn,
    )
    from kubeflow_tpu.train.checkpoint import CheckpointConfig
    from kubeflow_tpu.train.loop import TrainConfig, Trainer

    model = MnistCNN()
    return Trainer(
        init_params=make_init_fn(model),
        loss_fn=make_loss_fn(model),
        optimizer=optax.adam(3e-3),
        config=TrainConfig(
            mesh=MeshSpec.data_parallel(8),
            global_batch=32,
            steps=steps,
            log_every=1,
            checkpoint=CheckpointConfig(
                directory=str(ckpt_dir), save_every_steps=1,
                async_save=False, max_to_keep=10,
            ),
            **cfg_kw,
        ),
    )


def _data(start_step=0):
    from kubeflow_tpu.data.synthetic import (
        ClassPrototypeDataset, local_shard_iterator,
    )

    return local_shard_iterator(
        ClassPrototypeDataset(), 32, start_step=start_step
    )


def test_corrupt_latest_checkpoint_restore_falls_back(tmp_path, devices8):
    from kubeflow_tpu.train.checkpoint import (
        CheckpointConfig, Checkpointer, CorruptCheckpointError,
    )

    ckpt_dir = tmp_path / "ckpt"
    t1 = _mnist_trainer(4, ckpt_dir, resume="auto")
    t1.fit(lambda s: _data(s))

    step, victim = corrupt_checkpoint(ckpt_dir)  # flips a byte in step 4
    assert step == 4 and Path(victim).exists()

    cfg = CheckpointConfig(directory=str(ckpt_dir), async_save=False)
    with Checkpointer(cfg) as c:
        assert c.verify_step(4) is False  # manifest catches the flip
        assert c.verify_step(3) is True
        assert c.latest_step() == 4      # Orbax itself is none the wiser
        assert c.latest_valid_step() == 3
        # explicitly requested corrupt step: loud failure, no substitution
        with pytest.raises(CorruptCheckpointError):
            c.restore({"x": 0}, step=4)

    # fit(resume='auto') walks back to step 3 and re-trains 4..6
    t2 = _mnist_trainer(6, ckpt_dir, resume="auto")
    state, history = t2.fit(lambda s: _data(s))
    assert int(state.step) == 6
    assert [h["step"] for h in history] == [4, 5, 6]


def test_every_checkpoint_corrupt_raises(tmp_path, devices8):
    from kubeflow_tpu.train.checkpoint import (
        CheckpointConfig, Checkpointer, CorruptCheckpointError,
    )

    ckpt_dir = tmp_path / "ckpt"
    t1 = _mnist_trainer(2, ckpt_dir)
    state, _ = t1.fit(lambda s: _data(s))
    for step in (1, 2):
        corrupt_checkpoint(ckpt_dir, step)
    with Checkpointer(
        CheckpointConfig(directory=str(ckpt_dir), async_save=False)
    ) as c:
        assert c.latest_valid_step() is None
        with pytest.raises(CorruptCheckpointError, match="every checkpoint"):
            c.restore(state)


# --------------------------------------------------------------------- #
# preemption: SIGTERM → final checkpoint → 143 → exact-step resume
# --------------------------------------------------------------------- #


def test_preemption_sigterm_checkpoints_and_exits_143(tmp_path, devices8):
    from kubeflow_tpu.train.checkpoint import CheckpointConfig, Checkpointer
    from kubeflow_tpu.train.loop import Preempted

    ckpt_dir = tmp_path / "ckpt"
    # interval saves disabled (every 1000): the only checkpoint a preempted
    # run can leave is the forced preemption save
    trainer = _mnist_trainer(12, ckpt_dir)
    trainer.config.checkpoint = CheckpointConfig(
        directory=str(ckpt_dir), save_every_steps=1000, async_save=False
    )
    fired = []

    def deliver_sigterm(step, _metrics):
        if step >= 2 and not fired:
            fired.append(step)
            import os

            os.kill(os.getpid(), signal.SIGTERM)  # real signal delivery

    before = signal.getsignal(signal.SIGTERM)
    with pytest.raises(Preempted) as exc:
        trainer.fit(lambda s: _data(s), hooks=[deliver_sigterm])
    assert exc.value.code == 143  # retryable under RestartPolicy.EXIT_CODE
    preempt_step = exc.value.step
    assert preempt_step >= 2
    assert signal.getsignal(signal.SIGTERM) == before  # handler restored

    with Checkpointer(
        CheckpointConfig(directory=str(ckpt_dir), async_save=False)
    ) as c:
        assert c.latest_step() == preempt_step  # the forced final save
        assert c.verify_step(preempt_step) is True

    # resume is the exact continuation
    t2 = _mnist_trainer(12, ckpt_dir, resume="auto")
    state, history = t2.fit(lambda s: _data(s))
    assert int(state.step) == 12
    assert [h["step"] for h in history] == list(range(preempt_step + 1, 13))


def test_request_preemption_without_signal(tmp_path, devices8):
    """The non-main-thread delivery path: request_preemption() alone must
    trigger the same checkpoint-and-143 protocol."""
    from kubeflow_tpu.train.loop import Preempted

    trainer = _mnist_trainer(12, tmp_path / "ckpt")
    trainer.config.handle_sigterm = False

    def hook(step, _m):
        if step >= 2:
            trainer.request_preemption()

    with pytest.raises(Preempted) as exc:
        trainer.fit(lambda s: _data(s), hooks=[hook])
    assert exc.value.code == 143


def test_preempt_worker_grace_kill(tmp_path):
    """A worker that ignores SIGTERM is SIGKILLed at the grace deadline —
    and the gang still recovers (137 is retryable)."""
    code = (
        "import os, signal, time, sys;"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN);"
        "sys.exit(0) if os.environ['KFT_ATTEMPT'] != '0' else time.sleep(60)"
    )
    cluster = LocalCluster(
        fleet=Fleet.homogeneous(1, "2x2"),
        base_dir=str(tmp_path),
        restart_backoff_base=0.05,
        resync_period=0.05,
    )
    g0 = _counter_value("kft_chaos_injected_total", kind="preempt_grace_kill")
    with cluster:
        job = JobSpec(
            name="stubborn",
            replicas={
                "worker": ReplicaSpec(
                    replicas=1,
                    command=(PY, "-c", code),
                    restart_policy=RestartPolicy.EXIT_CODE,
                )
            },
        )
        uid = cluster.submit(job)
        plan = FaultPlan(faults=(PreemptWorker(index=None, grace_s=0.3),))
        report = ChaosRunner(cluster, uid, plan).drive(timeout=60)
        assert report["phase"] == "Succeeded"
        assert report["restart_count"] == 1
    assert _counter_value(
        "kft_chaos_injected_total", kind="preempt_grace_kill"
    ) == g0 + 1


# --------------------------------------------------------------------- #
# slice loss → gang requeue → recovery when capacity returns
# --------------------------------------------------------------------- #


def test_slice_loss_requeues_then_recovers(tmp_path):
    cluster = LocalCluster(
        fleet=Fleet.homogeneous(1, "2x2"),
        base_dir=str(tmp_path),
        restart_backoff_base=0.05,
        resync_period=0.05,
    )
    with cluster:
        job = JobSpec(
            name="slice-victim",
            replicas={
                "worker": ReplicaSpec(
                    replicas=2,
                    # long-lived on the doomed attempt, quick exit after the
                    # requeue relaunch — keeps the injection window wide and
                    # the test fast
                    command=(
                        PY, "-c",
                        "import os, time; time.sleep("
                        "5.0 if os.environ['KFT_ATTEMPT'] == '0' else 0.2)",
                    ),
                    tpu=TPURequest(chips=1),
                )
            },
        )
        uid = cluster.submit(job)
        deadline = time.time() + 20
        while time.time() < deadline:
            ws = cluster.workers.list(prefix=f"{uid}/")
            if ws and all(w.phase is WorkerPhase.RUNNING for _, w in ws):
                break
            time.sleep(0.02)
        runner = ChaosRunner(
            cluster, uid, FaultPlan(faults=(DropSlice(index=0),))
        )
        runner.poll()
        assert runner.done

        # the gang goes back through admission and waits as Queued
        deadline = time.time() + 20
        while time.time() < deadline:
            st = cluster.status(uid)
            if st and any(
                c.type is CT.QUEUED and c.status for c in st.conditions
            ):
                break
            time.sleep(0.02)
        st = cluster.status(uid)
        restarting = [c for c in st.conditions if c.type is CT.RESTARTING]
        assert restarting and restarting[0].reason == "SliceLost"

        # capacity returns → relaunch at attempt 1 → success, and slice
        # loss burned NO failure-backoff budget
        cluster.fleet.add_slice(Slice("slice-respawn", "2x2"))
        status = cluster.wait(uid, timeout=30)
        assert status.phase == "Succeeded"
        assert status.restart_count == 0
        assert all(
            w.restarts == 1
            for _, w in cluster.workers.list(prefix=f"{uid}/")
        )


# --------------------------------------------------------------------- #
# wedged worker (SIGSTOP): supervisor detection → gang recovery
# --------------------------------------------------------------------- #

#: beats by hand (no framework import → starts in milliseconds), exits 0
#: after a short life; a SIGSTOP freezes the beats without an exit.
BEAT_THEN_EXIT = """
import json, os, threading, time
workdir = os.environ["KFT_WORKDIR"]
rtype = os.environ["KFT_REPLICA_TYPE"]
index = os.environ["KFT_REPLICA_INDEX"]
attempt = int(os.environ["KFT_ATTEMPT"])
path = os.path.join(workdir, f"heartbeat-{rtype}-{index}.json")
def beat():
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"time": time.monotonic(), "pid": os.getpid(),
                   "step": 1, "attempt": attempt}, f)
    os.replace(tmp, path)
beat()
def pump():
    while True:
        beat(); time.sleep(0.05)
threading.Thread(target=pump, daemon=True).start()
# long-lived on attempt 0 (wide injection window for the SIGSTOP), quick
# clean exit once the supervisor-driven restart proves recovery
time.sleep(5.0 if attempt == 0 else 0.2)
"""


def test_wedge_worker_supervisor_recovers(tmp_path):
    cluster = LocalCluster(
        fleet=Fleet.homogeneous(1, "2x2"),
        base_dir=str(tmp_path),
        restart_backoff_base=0.05,
        resync_period=0.05,
    )
    with cluster:
        job = JobSpec(
            name="wedged",
            replicas={
                "worker": ReplicaSpec(
                    replicas=1,
                    command=(PY, "-c", BEAT_THEN_EXIT),
                    restart_policy=RestartPolicy.ON_FAILURE,
                )
            },
            elastic=ElasticPolicy(
                heartbeat_timeout_seconds=0.4,
                heartbeat_grace_seconds=10.0,
            ),
        )
        uid = cluster.submit(job)
        plan = FaultPlan(faults=(WedgeWorker(at_step=1, index=0),))
        report = ChaosRunner(cluster, uid, plan).drive(timeout=60)
        # frozen process never exits on its own; the supervisor must have
        # killed it (stale beat) and the restarted attempt finishes clean
        assert report["phase"] == "Succeeded"
        assert report["restart_count"] == 1
        assert not report["pending"]


# --------------------------------------------------------------------- #
# storage / transfer fault injection
# --------------------------------------------------------------------- #


def test_storage_faults_transient_failures_are_retried(tmp_path):
    from kubeflow_tpu.serve import storage

    src = tmp_path / "weights.bin"
    src.write_bytes(b"x" * 1024)
    with storage_faults(fail=2) as stats:
        out = storage.download(
            str(src), str(tmp_path / "dest"), retries=3, backoff_s=0.01
        )
    assert Path(out).read_bytes() == b"x" * 1024
    assert stats["failed"] == 2
    assert storage.verify(out, uri=str(src))


def test_storage_faults_exhausted_retries_surface(tmp_path):
    from kubeflow_tpu.serve import storage

    src = tmp_path / "weights.bin"
    src.write_bytes(b"y" * 64)
    with storage_faults(fail=5):
        with pytest.raises(RuntimeError, match="failed after 3 attempts"):
            storage.download(
                str(src), str(tmp_path / "dest"), retries=3, backoff_s=0.01
            )


def test_storage_faults_corruption_rejected_by_pin(tmp_path):
    """A silently-corrupting transfer must never satisfy an
    expected_sha256 pin — every attempt corrupts, so the download fails
    loudly instead of serving flipped bytes."""
    import hashlib

    from kubeflow_tpu.serve import storage

    payload = b"model-bytes" * 100
    src = tmp_path / "model.bin"
    src.write_bytes(payload)
    want = hashlib.sha256(payload).hexdigest()
    with storage_faults(corrupt_every=1) as stats:
        with pytest.raises(RuntimeError, match="checksum mismatch|failed"):
            storage.download(
                str(src), str(tmp_path / "dest"),
                retries=2, backoff_s=0.01, expected_sha256=want,
            )
    assert stats["corrupted"] >= 1
    # and WITHOUT the fault, the same pin succeeds (the harness restored
    # the fetcher registry on exit)
    out = storage.download(
        str(src), str(tmp_path / "dest2"), expected_sha256=want
    )
    assert Path(out).read_bytes() == payload


def test_storage_faults_cover_registry_scheme(tmp_path):
    """registry:// transfers flow through the same faultable seam: a
    transient flake on the blob copy is retried and the content-hash pin
    still holds end to end."""
    from kubeflow_tpu.registry.store import ModelStore, set_default_store
    from kubeflow_tpu.serve import storage

    payload = b"registered-model-bytes"
    src = tmp_path / "m.bin"
    src.write_bytes(payload)
    store = ModelStore(str(tmp_path / "registry"))
    set_default_store(store)
    try:
        store.register_version("chaos-model", str(src), stage="production")
        with storage_faults(fail=1) as stats:
            out = storage.download(
                "registry://chaos-model@production",
                str(tmp_path / "dest"), retries=3, backoff_s=0.01,
            )
        assert Path(out).read_bytes() == payload
        assert stats["failed"] == 1
    finally:
        set_default_store(None)
        store.close()


# --------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------- #


def test_kft_chaos_run_cli(tmp_path, capsys):
    import yaml

    from kubeflow_tpu.cli import main

    code = (
        "import os, sys, time;"
        "time.sleep(5.0) if os.environ['KFT_ATTEMPT'] == '0' "
        "else sys.exit(0)"
    )
    job = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {"name": "chaos-cli"},
        "spec": {
            "replicaSpecs": {
                "Worker": {
                    "replicas": 1,
                    "restartPolicy": "ExitCode",
                    "template": {
                        "spec": {
                            "containers": [
                                {"command": [PY, "-c", code]}
                            ]
                        }
                    },
                }
            }
        },
    }
    jf = tmp_path / "job.yaml"
    jf.write_text(yaml.safe_dump(job))
    pf = tmp_path / "plan.yaml"
    pf.write_text(yaml.safe_dump({
        "seed": 3,
        "faults": [{"kind": "CrashWorker", "index": 0, "sig": 9}],
    }))
    rc = main([
        "chaos", "run", "-f", str(jf), "--plan", str(pf), "--timeout", "60",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "job/chaos-cli: Succeeded" in out
    assert "fired CrashWorker" in out
