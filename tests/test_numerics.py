"""NaN/numerics discipline (VERDICT r3 missing #6; SURVEY.md §5.2):
poisoned-batch fault injection must fail fast with a located error; a clean
run must be unchanged."""

import numpy as np
import optax
import pytest

from kubeflow_tpu.core.mesh import MeshSpec
from kubeflow_tpu.data.synthetic import ClassPrototypeDataset, local_shard_iterator
from kubeflow_tpu.models.mnist_cnn import MnistCNN, make_init_fn, make_loss_fn
from kubeflow_tpu.train.loop import TrainConfig, Trainer
from kubeflow_tpu.train.metrics import MetricWriter, NonFiniteMetricError


def _trainer(**overrides):
    model = MnistCNN()
    cfg = dict(
        mesh=MeshSpec.data_parallel(8),
        global_batch=16,
        steps=4,
        log_every=1,
    )
    cfg.update(overrides)
    return Trainer(
        init_params=make_init_fn(model),
        loss_fn=make_loss_fn(model),
        optimizer=optax.adam(1e-3),
        config=TrainConfig(**cfg),
    )


def _poisoned_stream(poison_at: int):
    ds = ClassPrototypeDataset()

    def factory(start_step):
        def gen():
            it = local_shard_iterator(ds, 16, start_step=start_step)
            for step, (x, y) in enumerate(it, start=start_step):
                if step == poison_at:
                    x = x.copy()
                    x[0, 0, 0, 0] = np.nan  # one poisoned pixel
                yield x, y

        return gen()

    return factory


def test_clean_run_unchanged(devices8):
    state, history = _trainer().fit(_poisoned_stream(poison_at=10**9))
    assert int(state.step) == 4
    assert all(np.isfinite(h["loss"]) for h in history)


def test_poisoned_batch_fails_fast_default_mode(devices8):
    with pytest.raises(NonFiniteMetricError, match="step 3"):
        _trainer().fit(_poisoned_stream(poison_at=2))


def test_poisoned_batch_checkify_locates_the_nan(devices8):
    t = _trainer(check_numerics="checkify")
    with pytest.raises(Exception, match="(?i)nan"):
        t.fit(_poisoned_stream(poison_at=1))


def test_checkify_clean_run_matches_default(devices8):
    """checkify instrumentation must not change the math."""
    s1, h1 = _trainer().fit(_poisoned_stream(poison_at=10**9))
    s2, h2 = _trainer(check_numerics="checkify").fit(
        _poisoned_stream(poison_at=10**9)
    )
    np.testing.assert_allclose(
        [h["loss"] for h in h1], [h["loss"] for h in h2], rtol=1e-6
    )


def test_metric_writer_alarm_fires_on_every_rank():
    w = MetricWriter(None, is_writer=False)  # non-writer rank
    with pytest.raises(NonFiniteMetricError):
        w.write(7, {"loss": float("nan")})
    w2 = MetricWriter(None, is_writer=True, nan_alarm=False)
    w2.write(7, {"loss": float("nan")})  # explicit opt-out stays silent


@pytest.mark.slow
def test_loss_invariant_across_mesh_shapes(devices8):
    """SPMD determinism (SURVEY.md §5.2): the SAME model/seed/data must
    produce the same losses whether the 8 devices are laid out as pure DP,
    pure FSDP, hybrid DP x FSDP, or with tensor parallelism — resharding
    must never change the math."""
    import jax.numpy as jnp

    from kubeflow_tpu.data.synthetic import TokenLMDataset
    from kubeflow_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )
    from kubeflow_tpu.models.transformer import make_init_fn as t_init
    from kubeflow_tpu.models.transformer import make_loss_fn as t_loss
    from kubeflow_tpu.parallel.sharding import transformer_rules

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        attn_impl="reference", dtype=jnp.float32, embed_impl="onehot",
    )
    ds = TokenLMDataset(vocab_size=128, seq_len=32)

    def run(spec):
        model = TransformerLM(cfg)
        trainer = Trainer(
            init_params=t_init(model, 32, 8),
            loss_fn=t_loss(model),
            optimizer=optax.adamw(1e-3),
            config=TrainConfig(
                mesh=spec, global_batch=16, steps=3, log_every=1,
            ),
            param_spec_fn=transformer_rules(),
        )
        _, history = trainer.fit(
            lambda s: local_shard_iterator(ds, 16, start_step=s)
        )
        return [h["loss"] for h in history]

    losses = {
        "dp8": run(MeshSpec(data=8)),
        "fsdp8": run(MeshSpec(fsdp=8)),
        "dp2xfsdp4": run(MeshSpec(data=2, fsdp=4)),
        "fsdp4xtp2": run(MeshSpec(fsdp=4, model=2)),
    }
    ref = losses["dp8"]
    for name, ls in losses.items():
        np.testing.assert_allclose(
            ls, ref, rtol=2e-5,
            err_msg=f"mesh layout {name} changed the training math",
        )
