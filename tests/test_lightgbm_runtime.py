"""LightGBM text-checkpoint runtime (serve/lightgbm_runtime.py): the
device program must match an INDEPENDENT walker implementing LightGBM's
published traversal semantics (<= thresholds, negative-child leaves,
per-node decision_type missing handling) on randomly generated boosters
— the lgbserver row of SURVEY.md §2.2 without a lightgbm dependency."""

from __future__ import annotations

import math

import numpy as np
import pytest

from kubeflow_tpu.serve.lightgbm_runtime import (
    LightGBMRuntimeModel,
    parse_lightgbm_txt,
)
from kubeflow_tpu.serve.xgboost_runtime import build_device_predict

# ---------------------------------------------------------------- generator


def _random_tree(rng, n_feat, n_leaves):
    """Random LightGBM tree in the text format's parallel-array form.
    Children: >=0 internal index, <0 leaf ref (-k-1). decision_type mixes
    NaN-missing (8|dl) and None-missing (dl only) nodes."""
    inner = n_leaves - 1
    # random topology: grow by splitting a random leaf slot
    lc, rc = [None] * inner, [None] * inner
    open_slots = [(0, "l"), (0, "r")]
    next_internal, next_leaf = 1, 0
    rng.shuffle(open_slots)
    while open_slots:
        node, side = open_slots.pop()
        # choose: internal (if available) or leaf
        if next_internal < inner and (
            rng.random() < 0.5
            or len(open_slots) + 1 < inner - next_internal + 1
        ):
            child = next_internal
            next_internal += 1
            new = [(child, "l"), (child, "r")]
            open_slots.extend(new)
            rng.shuffle(open_slots)
        else:
            child = -(next_leaf + 1)
            next_leaf += 1
        if side == "l":
            lc[node] = child
        else:
            rc[node] = child
    assert next_leaf == n_leaves and next_internal == inner
    return {
        "num_leaves": n_leaves,
        "split_feature": [int(rng.integers(0, n_feat)) for _ in range(inner)],
        "threshold": [round(float(rng.normal()), 4) for _ in range(inner)],
        "decision_type": [
            int(rng.choice([2, 0, 8, 10])) for _ in range(inner)
        ],
        "left_child": lc,
        "right_child": rc,
        "leaf_value": [round(float(rng.normal()), 4) for _ in range(n_leaves)],
    }


def _to_text(trees, *, objective="regression", num_class=1, n_feat=4):
    lines = [
        "tree",
        "version=v4",
        f"num_class={num_class}",
        f"num_tree_per_iteration={num_class}",
        f"max_feature_idx={n_feat - 1}",
        f"objective={objective}",
        "feature_names=" + " ".join(f"f{i}" for i in range(n_feat)),
        "",
    ]
    for i, t in enumerate(trees):
        lines += [f"Tree={i}", f"num_leaves={t['num_leaves']}", "num_cat=0"]
        for key in ("split_feature", "threshold", "decision_type",
                    "left_child", "right_child", "leaf_value"):
            lines.append(f"{key}=" + " ".join(str(v) for v in t[key]))
        lines.append("")
    lines += ["end of trees", ""]
    return "\n".join(lines)


def _oracle_margin(trees, x, num_class=1):
    """Independent traversal, straight off LightGBM's documented
    semantics — never touches the runtime's parser or arrays."""
    out = np.zeros((x.shape[0], num_class))
    for r in range(x.shape[0]):
        for ti, t in enumerate(trees):
            if t["num_leaves"] == 1:
                out[r, ti % num_class] += t["leaf_value"][0]
                continue
            node = 0
            while node >= 0:
                v = x[r, t["split_feature"][node]]
                dt = t["decision_type"][node]
                if math.isnan(v):
                    if ((dt >> 2) & 3) == 2:        # NaN-missing node
                        go_left = bool(dt & 2)
                    else:                            # None-missing: NaN→0
                        go_left = 0.0 <= t["threshold"][node]
                else:
                    go_left = v <= t["threshold"][node]
                node = t["left_child" if go_left else "right_child"][node]
            out[r, ti % num_class] += t["leaf_value"][-node - 1]
    return out


# ------------------------------------------------------------------ parity


def _fuzz_once(seed, objective, num_class=1, with_nan=True):
    rng = np.random.default_rng(seed)
    n_feat = 5
    trees = [
        _random_tree(rng, n_feat, int(rng.integers(2, 9)))
        for _ in range(4 * num_class)
    ]
    text = _to_text(
        trees, objective=objective, num_class=num_class, n_feat=n_feat
    )
    x = rng.normal(size=(32, n_feat)).astype(np.float32)
    if with_nan:
        x[rng.random(x.shape) < 0.15] = np.nan
    return trees, text, x


@pytest.mark.parametrize("seed", range(6))
def test_regression_parity_fuzz(tmp_path, seed):
    trees, text, x = _fuzz_once(seed, "regression")
    p = tmp_path / "model.txt"
    p.write_text(text)
    fwd = build_device_predict(parse_lightgbm_txt(str(p)))
    got = np.asarray(fwd(x))
    want = _oracle_margin(trees, x)[:, 0]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_binary_and_multiclass_links(tmp_path):
    trees, text, x = _fuzz_once(7, "binary sigmoid:1")
    p = tmp_path / "model.txt"
    p.write_text(text)
    fwd = build_device_predict(parse_lightgbm_txt(str(p)))
    want = 1.0 / (1.0 + np.exp(-_oracle_margin(trees, x)[:, 0]))
    np.testing.assert_allclose(np.asarray(fwd(x)), want, rtol=1e-5, atol=1e-6)

    trees, text, x = _fuzz_once(9, "multiclass num_class:3", num_class=3)
    (tmp_path / "mc.txt").write_text(text)
    fwd = build_device_predict(parse_lightgbm_txt(str(tmp_path / "mc.txt")))
    m = _oracle_margin(trees, x, num_class=3)
    e = np.exp(m - m.max(axis=1, keepdims=True))
    np.testing.assert_allclose(
        np.asarray(fwd(x)), e / e.sum(axis=1, keepdims=True),
        rtol=1e-5, atol=1e-6,
    )


def test_le_boundary_is_exact(tmp_path):
    """The <= vs < conversion must hold AT the threshold value."""
    tree = {
        "num_leaves": 2, "split_feature": [0], "threshold": [1.25],
        "decision_type": [2], "left_child": [-1], "right_child": [-2],
        "leaf_value": [10.0, 20.0],
    }
    p = tmp_path / "model.txt"
    p.write_text(_to_text([tree], n_feat=1))
    fwd = build_device_predict(parse_lightgbm_txt(str(p)))
    x = np.asarray(
        [[1.25], [np.nextafter(np.float32(1.25), np.float32(2))], [1.0]],
        np.float32,
    )
    np.testing.assert_allclose(np.asarray(fwd(x)), [10.0, 20.0, 10.0])


def test_rejects_unsupported_and_serves_e2e(tmp_path):
    bad = tmp_path / "bad.txt"
    bad.write_text(_to_text(
        [_random_tree(np.random.default_rng(0), 3, 4)], objective="poisson"
    ))
    with pytest.raises(RuntimeError, match="not supported"):
        parse_lightgbm_txt(str(bad))

    cat = _random_tree(np.random.default_rng(1), 3, 4)
    text = _to_text([cat]).replace("num_cat=0", "num_cat=1")
    (tmp_path / "cat.txt").write_text(text)
    with pytest.raises(RuntimeError, match="categorical"):
        parse_lightgbm_txt(str(tmp_path / "cat.txt"))

    zero_missing = dict(cat, decision_type=[4] * 3)
    (tmp_path / "zm.txt").write_text(_to_text([zero_missing]))
    with pytest.raises(RuntimeError, match="zero_as_missing"):
        parse_lightgbm_txt(str(tmp_path / "zm.txt"))

    # registry → model lifecycle → v1 predict round-trip
    from kubeflow_tpu.serve.spec import PredictorSpec
    from kubeflow_tpu.serve.runtimes import default_registry

    trees, text, x = _fuzz_once(3, "regression", with_nan=False)
    mdir = tmp_path / "mnt"
    mdir.mkdir()
    (mdir / "model.txt").write_text(text)
    rt = default_registry().resolve(
        PredictorSpec(model_format="lightgbm", storage_uri=f"file://{mdir}")
    )
    assert rt.name == "kubeflow-tpu-lightgbm"
    m = rt.factory("lgb", str(mdir))
    assert isinstance(m, LightGBMRuntimeModel)
    m.load()
    rows = m.preprocess({"instances": x[:3].tolist()})
    out = m.postprocess(m.predict(rows))
    np.testing.assert_allclose(
        out["predictions"], _oracle_margin(trees, x[:3])[:, 0],
        rtol=1e-5, atol=1e-5,
    )


def test_le_boundary_nonrepresentable_midpoint(tmp_path):
    """LightGBM thresholds are double midpoints between observed feature
    values; when the training data is float32-typed that midpoint is NOT
    float32-representable and round-to-nearest picks the UPPER value
    about half the time. The <=→< conversion must round the double
    toward −inf first (ADVICE r5): an input exactly equal to the upper
    neighbour sits ABOVE the threshold and must route right."""
    lo = float(np.nextafter(np.float32(1.0), np.float32(2.0)))   # 1+2^-23
    hi = float(np.nextafter(np.float32(lo), np.float32(2.0)))    # 1+2^-22
    t = (lo + hi) / 2.0                       # double midpoint, ties to hi
    assert float(np.float32(t)) == hi         # round-half-even rounds UP
    tree = {
        "num_leaves": 2, "split_feature": [0], "threshold": [repr(t)],
        "decision_type": [2], "left_child": [-1], "right_child": [-2],
        "leaf_value": [10.0, 20.0],
    }
    p = tmp_path / "model.txt"
    p.write_text(_to_text([tree], n_feat=1))
    fwd = build_device_predict(parse_lightgbm_txt(str(p)))
    x = np.asarray([[lo], [hi]], np.float32)
    # lo <= t → left(10); hi > t → right(20). The pre-fix conversion sent
    # hi left because nextafter started from the rounded-UP threshold.
    np.testing.assert_allclose(np.asarray(fwd(x)), [10.0, 20.0])
