"""PMML runtime (serve/pmml_runtime.py): RegressionModel on the MXU
matmul path, TreeModel/MiningModel forests on the shared GBDT walk —
checked against hand-computed expectations and an independent tree
evaluator over the XML (SURVEY.md §2.2 "Other runtimes" pmml row)."""

from __future__ import annotations

import numpy as np
import pytest

from kubeflow_tpu.serve.pmml_runtime import PMMLRuntimeModel, parse_pmml

HEADER = """<?xml version="1.0"?>
<PMML xmlns="http://www.dmg.org/PMML-4_4" version="4.4">
 <DataDictionary>
  <DataField name="y" optype="continuous" dataType="double"/>
  <DataField name="x0" optype="continuous" dataType="double"/>
  <DataField name="x1" optype="continuous" dataType="double"/>
 </DataDictionary>
"""

REGRESSION = HEADER + """
 <RegressionModel functionName="regression">
  <MiningSchema>
   <MiningField name="y" usageType="target"/>
   <MiningField name="x0"/><MiningField name="x1"/>
  </MiningSchema>
  <RegressionTable intercept="1.5">
   <NumericPredictor name="x0" coefficient="2.0"/>
   <NumericPredictor name="x1" coefficient="-0.5"/>
  </RegressionTable>
 </RegressionModel>
</PMML>
"""

LOGISTIC = HEADER + """
 <RegressionModel functionName="classification" normalizationMethod="logit">
  <MiningSchema>
   <MiningField name="y" usageType="target"/>
   <MiningField name="x0"/><MiningField name="x1"/>
  </MiningSchema>
  <RegressionTable intercept="0.0" targetCategory="1">
   <NumericPredictor name="x0" coefficient="3.0"/>
   <NumericPredictor name="x1" coefficient="1.0"/>
  </RegressionTable>
 </RegressionModel>
</PMML>
"""

TREE = HEADER + """
 <TreeModel functionName="regression">
  <MiningSchema>
   <MiningField name="y" usageType="target"/>
   <MiningField name="x0"/><MiningField name="x1"/>
  </MiningSchema>
  <Node>
   <True/>
   <Node score="-1.0">
    <SimplePredicate field="x0" operator="lessOrEqual" value="0.5"/>
    <Node score="10.0">
     <SimplePredicate field="x1" operator="lessThan" value="-1.0"/>
    </Node>
    <Node score="20.0">
     <SimplePredicate field="x1" operator="greaterOrEqual" value="-1.0"/>
    </Node>
   </Node>
   <Node score="30.0">
    <SimplePredicate field="x0" operator="greaterThan" value="0.5"/>
   </Node>
  </Node>
 </TreeModel>
</PMML>
"""

FOREST = HEADER + """
 <MiningModel functionName="regression">
  <MiningSchema>
   <MiningField name="y" usageType="target"/>
   <MiningField name="x0"/><MiningField name="x1"/>
  </MiningSchema>
  <Segmentation multipleModelMethod="average">
   <Segment><True/>
    <TreeModel functionName="regression">
     <Node><True/>
      <Node score="2.0">
       <SimplePredicate field="x0" operator="lessOrEqual" value="0.0"/>
      </Node>
      <Node score="4.0">
       <SimplePredicate field="x0" operator="greaterThan" value="0.0"/>
      </Node>
     </Node>
    </TreeModel>
   </Segment>
   <Segment><True/>
    <TreeModel functionName="regression">
     <Node><True/>
      <Node score="10.0">
       <SimplePredicate field="x1" operator="lessOrEqual" value="1.0"/>
      </Node>
      <Node score="20.0">
       <SimplePredicate field="x1" operator="greaterThan" value="1.0"/>
      </Node>
     </Node>
    </TreeModel>
   </Segment>
  </Segmentation>
 </MiningModel>
</PMML>
"""


def _runtime(tmp_path, doc, name="m"):
    p = tmp_path / f"{name}.pmml"
    p.write_text(doc)
    m = PMMLRuntimeModel(name, str(p))
    m.load()
    return m


def test_regression_model_matmul(tmp_path):
    m = _runtime(tmp_path, REGRESSION)
    x = np.asarray([[1.0, 2.0], [0.0, 4.0]], np.float32)
    out = m.predict(m.preprocess({"instances": x.tolist()}))
    # 1.5 + 2*x0 - 0.5*x1, by hand
    np.testing.assert_allclose(out, [2.5, -0.5], rtol=1e-6)


def test_logistic_link(tmp_path):
    m = _runtime(tmp_path, LOGISTIC)
    x = np.asarray([[0.0, 0.0], [1.0, 1.0]], np.float32)
    out = m.predict(x)
    want = 1 / (1 + np.exp(-(3 * x[:, 0] + x[:, 1])))
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_tree_model_walk(tmp_path):
    m = _runtime(tmp_path, TREE)
    cases = [
        ([0.5, -2.0], 10.0),   # x0<=0.5 (boundary!), x1<-1
        ([0.5, -1.0], 20.0),   # x1 exactly -1: NOT < -1
        ([0.6, 0.0], 30.0),    # x0>0.5
        ([0.0, 5.0], 20.0),
    ]
    out = m.predict(np.asarray([c[0] for c in cases], np.float32))
    np.testing.assert_allclose(out, [c[1] for c in cases], rtol=1e-6)


def test_forest_average(tmp_path):
    m = _runtime(tmp_path, FOREST)
    x = np.asarray([[-1.0, 0.0], [1.0, 2.0]], np.float32)
    out = m.predict(x)
    # average of (2|4) and (10|20): [-1,0] → (2+10)/2; [1,2] → (4+20)/2
    np.testing.assert_allclose(out, [6.0, 12.0], rtol=1e-6)


def test_weighted_average_is_a_mean(tmp_path):
    """weightedAverage divides by the weight sum (PMML semantics) — a
    weighted SUM would scale predictions by sum(weights)."""
    doc = FOREST.replace(
        'multipleModelMethod="average"', 'multipleModelMethod="weightedAverage"'
    ).replace("<Segment><True/>", '<Segment weight="2.0"><True/>')
    m = _runtime(tmp_path, doc, "wavg")
    out = m.predict(np.asarray([[-1.0, 0.0]], np.float32))
    # both weights 2.0: (2*2 + 2*10)/(2+2) = 6.0, same as plain average
    np.testing.assert_allclose(out, [6.0], rtol=1e-6)


def test_first_match_order_fails_closed(tmp_path):
    """PMML evaluates children in document order; shapes this walker
    cannot represent must be parse errors, never silent misroutes."""
    # first child <True/> would always win in PMML — reject
    true_first = TREE.replace(
        """<Node score="-1.0">
    <SimplePredicate field="x0" operator="lessOrEqual" value="0.5"/>""",
        """<Node score="-1.0">
    <True/>""",
    )
    (tmp_path / "tf.pmml").write_text(true_first)
    with pytest.raises(RuntimeError, match="first child"):
        parse_pmml(str(tmp_path / "tf.pmml"))
    # non-complementary second predicate (different field) — reject
    noncomp = TREE.replace(
        '<SimplePredicate field="x0" operator="greaterThan" value="0.5"/>',
        '<SimplePredicate field="x1" operator="greaterThan" value="0.5"/>',
    )
    (tmp_path / "nc.pmml").write_text(noncomp)
    with pytest.raises(RuntimeError, match="not the\n?.*complement|complement"):
        parse_pmml(str(tmp_path / "nc.pmml"))


def test_fail_closed_and_registry(tmp_path):
    compound = TREE.replace(
        '<SimplePredicate field="x0" operator="lessOrEqual" value="0.5"/>',
        '<CompoundPredicate booleanOperator="and">'
        '<SimplePredicate field="x0" operator="lessOrEqual" value="0.5"/>'
        "</CompoundPredicate>",
    )
    p = tmp_path / "c.pmml"
    p.write_text(compound)
    with pytest.raises(RuntimeError, match="SimplePredicate or True"):
        parse_pmml(str(p))

    (tmp_path / "bad.pmml").write_text("<NotPMML/>")
    with pytest.raises(RuntimeError, match="not <PMML>"):
        parse_pmml(str(tmp_path / "bad.pmml"))

    (tmp_path / "n.pmml").write_text(
        HEADER + "<NeuralNetwork/></PMML>"
    )
    with pytest.raises(RuntimeError, match="no supported model element"):
        parse_pmml(str(tmp_path / "n.pmml"))

    # registry resolution + feature-count contract
    from kubeflow_tpu.serve.runtimes import default_registry
    from kubeflow_tpu.serve.spec import PredictorSpec

    rt = default_registry().resolve(
        PredictorSpec(model_format="pmml", storage_uri="file:///x")
    )
    assert rt.name == "kubeflow-tpu-pmml"
    m = _runtime(tmp_path, REGRESSION, "reg")
    with pytest.raises(ValueError, match="expects 2 features"):
        m.preprocess({"instances": [[1.0, 2.0, 3.0]]})


def test_fuzz_forest_against_independent_walker(tmp_path):
    """Random forests serialized to PMML, device walk vs a direct XML
    evaluator that implements PMML predicate semantics from scratch."""
    import xml.etree.ElementTree as ET

    rng = np.random.default_rng(0)
    n_feat = 3

    def rand_node(depth):
        if depth == 0 or rng.random() < 0.3:
            return f'<Node score="{round(float(rng.normal()), 3)}">%PRED%</Node>'
        f = int(rng.integers(0, n_feat))
        t = round(float(rng.normal()), 3)
        op_l, op_r = (
            ("lessOrEqual", "greaterThan")
            if rng.random() < 0.5 else ("lessThan", "greaterOrEqual")
        )
        left = rand_node(depth - 1).replace(
            "%PRED%",
            f'<SimplePredicate field="x{f}" operator="{op_l}" value="{t}"/>',
        )
        right = rand_node(depth - 1).replace(
            "%PRED%",
            f'<SimplePredicate field="x{f}" operator="{op_r}" value="{t}"/>',
        )
        return f"<Node>%PRED%{left}{right}</Node>"

    def eval_node(el, x):
        kids = [c for c in el if c.tag.endswith("Node")]
        if not kids:
            return float(el.get("score"))
        for kid in kids:
            sp = next((c for c in kid if c.tag.endswith("SimplePredicate")), None)
            v = x[int(sp.get("field")[1:])]
            t = float(sp.get("value"))
            ok = {
                "lessOrEqual": v <= t, "lessThan": v < t,
                "greaterThan": v > t, "greaterOrEqual": v >= t,
            }[sp.get("operator")]
            if ok:
                return eval_node(kid, x)
        raise AssertionError("no branch matched")

    trees = [
        rand_node(3).replace("%PRED%", "<True/>") for _ in range(5)
    ]
    header = (
        '<?xml version="1.0"?><PMML version="4.4"><DataDictionary>'
        + "".join(
            f'<DataField name="x{i}" optype="continuous"/>'
            for i in range(n_feat)
        )
        + "</DataDictionary>"
    )
    doc = (
        header
        + '<MiningModel functionName="regression">'
        + '<Segmentation multipleModelMethod="sum">'
        + "".join(
            f"<Segment><True/>"
            f'<TreeModel functionName="regression">{t}</TreeModel>'
            f"</Segment>"
            for t in trees
        )
        + "</Segmentation></MiningModel></PMML>"
    )
    p = tmp_path / "f.pmml"
    p.write_text(doc)
    m = PMMLRuntimeModel("f", str(p))
    m.load()
    x = rng.normal(size=(64, n_feat)).astype(np.float32)
    got = m.predict(x)
    roots = [
        next(c for c in ET.fromstring(f"<w>{t}</w>") if c.tag == "Node")
        for t in trees
    ]
    want = [
        sum(eval_node(r, row) for r in roots) for row in x
    ]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def _split_doc(op: str, t: float) -> str:
    comp = {"lessOrEqual": "greaterThan", "lessThan": "greaterOrEqual"}[op]
    return HEADER + f"""
 <TreeModel functionName="regression">
  <MiningSchema>
   <MiningField name="y" usageType="target"/>
   <MiningField name="x0"/><MiningField name="x1"/>
  </MiningSchema>
  <Node><True/>
   <Node score="10.0">
    <SimplePredicate field="x0" operator="{op}" value="{t!r}"/>
   </Node>
   <Node score="20.0">
    <SimplePredicate field="x0" operator="{comp}" value="{t!r}"/>
   </Node>
  </Node>
 </TreeModel>
</PMML>
"""


def test_threshold_ulp_boundaries(tmp_path):
    """Non-float32-representable thresholds must convert exactly
    (ADVICE r5): round-to-nearest casts land a ULP off on ~half of all
    midpoint thresholds, misrouting inputs equal to the rounded value."""
    lo = float(np.nextafter(np.float32(1.0), np.float32(2.0)))
    hi = float(np.nextafter(np.float32(lo), np.float32(2.0)))
    # lessOrEqual, midpoint that ROUNDS UP in float32: hi > t goes right
    t_up = (lo + hi) / 2.0
    assert float(np.float32(t_up)) == hi
    m = _runtime(tmp_path, _split_doc("lessOrEqual", t_up), "ule")
    out = m.predict(np.asarray([[lo, 0.0], [hi, 0.0]], np.float32))
    np.testing.assert_allclose(out, [10.0, 20.0])
    # lessThan, midpoint that ROUNDS DOWN in float32: 1.0 < t goes left
    t_dn = (1.0 + lo) / 2.0
    assert float(np.float32(t_dn)) == 1.0
    m = _runtime(tmp_path, _split_doc("lessThan", t_dn), "ult")
    out = m.predict(np.asarray([[1.0, 0.0], [lo, 0.0]], np.float32))
    np.testing.assert_allclose(out, [10.0, 20.0])


def test_deep_node_chain_fails_closed(tmp_path):
    """A degenerate ~1000-level Node chain must be a clear RuntimeError,
    not an uncontrolled RecursionError (ADVICE r5)."""
    depth = 1200
    pair = (
        '<Node score="0.0"><SimplePredicate field="x0"'
        ' operator="lessOrEqual" value="0.25"/></Node>'
        '<Node score="1.0"><SimplePredicate field="x0"'
        ' operator="greaterThan" value="0.25"/></Node>'
    )
    for i in range(depth):
        pair = (
            f'<Node><SimplePredicate field="x0" operator="lessOrEqual"'
            f' value="{i}.5"/>{pair}</Node>'
            f'<Node score="1.0"><SimplePredicate field="x0"'
            f' operator="greaterThan" value="{i}.5"/></Node>'
        )
    doc = HEADER + (
        '<TreeModel functionName="regression"><Node><True/>'
        + pair
        + "</Node></TreeModel></PMML>"
    )
    p = tmp_path / "deep.pmml"
    p.write_text(doc)
    with pytest.raises(RuntimeError, match="deeper than"):
        parse_pmml(str(p))


def test_classification_shapes_fail_closed(tmp_path):
    """functionName='classification' outside the supported envelope must
    be a parse error, never silently-served raw margins (ADVICE r5)."""
    # classification RegressionModel with normalizationMethod none
    raw_margin = LOGISTIC.replace(' normalizationMethod="logit"', "")
    (tmp_path / "rm.pmml").write_text(raw_margin)
    with pytest.raises(RuntimeError, match="normalizationMethod"):
        parse_pmml(str(tmp_path / "rm.pmml"))
    # classification TreeModel
    ctree = TREE.replace(
        '<TreeModel functionName="regression">',
        '<TreeModel functionName="classification">',
    )
    (tmp_path / "ct.pmml").write_text(ctree)
    with pytest.raises(RuntimeError, match="classification"):
        parse_pmml(str(tmp_path / "ct.pmml"))
    # classification MiningModel of TreeModels
    cmm = FOREST.replace(
        '<MiningModel functionName="regression">',
        '<MiningModel functionName="classification">',
    )
    (tmp_path / "cm.pmml").write_text(cmm)
    with pytest.raises(RuntimeError, match="classification"):
        parse_pmml(str(tmp_path / "cm.pmml"))
    # the supported classification shape still loads
    m = _runtime(tmp_path, LOGISTIC, "ok")
    assert m.ready
