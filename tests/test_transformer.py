"""Flagship transformer: impl equivalence across parallel strategies,
sharded training with FSDP+TP rules, MoE variant, remat."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.core.mesh import Axis, MeshSpec, build_mesh, mesh_context
from kubeflow_tpu.data.synthetic import TokenLMDataset, local_shard_iterator
from kubeflow_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    make_init_fn,
    make_loss_fn,
)
from kubeflow_tpu.parallel.expert import MoEConfig
from kubeflow_tpu.parallel.sharding import transformer_rules
from kubeflow_tpu.train.loop import TrainConfig, Trainer

VOCAB, SEQ, DM, HEADS = 128, 256, 64, 8


def _cfg(**kw):
    base = dict(
        vocab_size=VOCAB,
        d_model=DM,
        n_layers=2,
        n_heads=HEADS,
        d_ff=128,
        attn_impl="reference",
        interpret_kernels=True,
    )
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def tokens():
    return jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, (4, SEQ)), jnp.int32
    )


@pytest.fixture(scope="module")
def ref_setup(tokens):
    cfg = _cfg()
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    return params, logits


def test_forward_shape_and_finite(ref_setup, tokens):
    _, logits = ref_setup
    assert logits.shape == (4, SEQ, VOCAB)
    assert bool(jnp.isfinite(logits).all())


@pytest.mark.parametrize(
    "impl,mesh_kw",
    [
        ("flash", {}),                       # no mesh: direct pallas call
        ("flash", {"data": 2, "model": 4}),  # TP head sharding via shard_map
        ("ring", {"data": 2, "seq": 4}),     # context parallel
        ("ulysses", {"seq": 8}),             # sequence parallel
    ],
)
def test_attention_impls_match_reference(ref_setup, tokens, devices8, impl, mesh_kw):
    params, ref_logits = ref_setup
    cfg = _cfg(attn_impl=impl)
    model = TransformerLM(cfg)
    if mesh_kw:
        mesh = build_mesh(MeshSpec(**mesh_kw))
        with mesh_context(mesh):
            logits = jax.jit(
                lambda p, t: model.apply({"params": p}, t)
            )(params, tokens)
    else:
        logits = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), atol=2e-3,
        err_msg=f"{impl} vs reference ({mesh_kw})",
    )


def test_flash_rejects_seq_sharding(ref_setup, tokens, devices8):
    params, _ = ref_setup
    model = TransformerLM(_cfg(attn_impl="flash"))
    mesh = build_mesh(MeshSpec(seq=8))
    with mesh_context(mesh):
        with pytest.raises(ValueError, match="ring|ulysses"):
            jax.jit(lambda p, t: model.apply({"params": p}, t))(params, tokens)


def _train(cfg_model, mesh_spec, steps=6, rules=None, seq=64, batch=16):
    model = TransformerLM(cfg_model)
    trainer = Trainer(
        init_params=make_init_fn(model, seq, mesh_spec.batch_partitions),
        loss_fn=make_loss_fn(model),
        optimizer=optax.adam(1e-2),
        config=TrainConfig(
            mesh=mesh_spec, global_batch=batch, steps=steps, log_every=2
        ),
        param_spec_fn=rules,
    )
    ds = TokenLMDataset(vocab_size=cfg_model.vocab_size, seq_len=seq)
    state, history = trainer.fit(
        lambda s: local_shard_iterator(ds, batch, start_step=s)
    )
    return trainer, state, history


def test_train_fsdp_tp_sharded(devices8):
    cfg = _cfg(n_layers=2, attn_impl="flash")
    rules = transformer_rules()
    trainer, state, history = _train(cfg, MeshSpec(data=2, fsdp=2, model=2), rules=rules)
    assert history[-1]["loss"] < history[0]["loss"]
    # check a TP param really is sharded over model and fsdp
    q = state.params["layers_0"]["attn"]["q_proj"]["kernel"]
    spec = q.sharding.spec
    assert spec == (Axis.FSDP, Axis.MODEL), spec
    # optimizer moments colocated with params
    mu_q = state.opt_state[0].mu["layers_0"]["attn"]["q_proj"]["kernel"]
    assert mu_q.sharding.spec == q.sharding.spec


@pytest.mark.slow
def test_train_ring_attention_long_context(devices8):
    cfg = _cfg(n_layers=1, attn_impl="ring", attn_block_q=64, attn_block_k=64)
    _, _, history = _train(cfg, MeshSpec(data=2, seq=4), seq=256)
    assert history[-1]["loss"] < history[0]["loss"]


def test_train_moe_expert_parallel(devices8):
    cfg = _cfg(
        n_layers=2,
        attn_impl="reference",
        moe_every=2,
        moe=MoEConfig(num_experts=4, expert_dim=64, top_k=2),
    )
    trainer, state, history = _train(
        cfg, MeshSpec(data=2, expert=4), rules=transformer_rules()
    )
    assert history[-1]["loss"] < history[0]["loss"]
    assert "moe_aux" in history[0]
    up = state.params["layers_1"]["experts"]["up_kernel"]
    assert up.sharding.spec[0] == Axis.EXPERT


def test_remat_matches(ref_setup, tokens):
    params, ref_logits = ref_setup
    model = TransformerLM(_cfg(remat=True))
    logits = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), atol=1e-5
    )


def test_bidirectional_encoder_mode(tokens):
    cfg = _cfg(causal=False, use_rope=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    assert "pos_embedding" in params
    logits = model.apply({"params": params}, tokens)
    # bidirectional: flipping future tokens must change position-0 logits
    toks2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % VOCAB)
    logits2 = model.apply({"params": params}, toks2)
    assert not np.allclose(np.asarray(logits[:, 0]), np.asarray(logits2[:, 0]))


def test_embed_onehot_matches_gather(ref_setup, tokens):
    # same params, same numbers — onehot is the SPMD-clean lookup form
    params, ref_logits = ref_setup
    model = TransformerLM(_cfg(embed_impl="onehot"))
    logits = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), atol=1e-5
    )


# ----------------------- grouped-query attention ----------------------- #

def test_gqa_equals_mha_with_tied_kv_groups():
    """A GQA model must equal an MHA model whose k/v kernels tie each
    group of query heads to one shared kv head — GQA is a weight-sharing
    pattern, not new math."""
    import numpy as np

    cfg_gqa = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, causal=True, attn_impl="reference", dtype=jnp.float32,
    )
    cfg_mha = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4,
        d_ff=64, causal=True, attn_impl="reference", dtype=jnp.float32,
    )
    m_gqa, m_mha = TransformerLM(cfg_gqa), TransformerLM(cfg_mha)
    p_gqa = m_gqa.init(jax.random.PRNGKey(5), jnp.zeros((1, 4), jnp.int32))[
        "params"
    ]
    D = cfg_gqa.head_dim
    groups = cfg_gqa.n_heads // cfg_gqa.kv_heads

    def tie(kernel):  # (d_model, Hkv*D) → (d_model, H*D), group-shared
        cols = [kernel[:, g * D:(g + 1) * D] for g in range(cfg_gqa.kv_heads)]
        return jnp.concatenate(
            [cols[j // groups] for j in range(cfg_gqa.n_heads)], axis=1
        )

    p_mha = jax.tree_util.tree_map(lambda x: x, p_gqa)  # copy structure
    p_mha = jax.device_get(p_mha)
    for layer in [k for k in p_mha if k.startswith("layers_")]:
        attn = p_mha[layer]["attn"]
        attn["k_proj"]["kernel"] = tie(attn["k_proj"]["kernel"])
        attn["v_proj"]["kernel"] = tie(attn["v_proj"]["kernel"])

    toks = jax.random.randint(jax.random.PRNGKey(7), (2, 12), 0, 64)
    out_gqa = m_gqa.apply({"params": p_gqa}, toks)
    out_mha = m_mha.apply({"params": p_mha}, toks)
    np.testing.assert_allclose(
        np.asarray(out_gqa), np.asarray(out_mha), rtol=2e-5, atol=1e-5
    )


def test_gqa_cache_decode_matches_full_forward():
    """KV-cache decode with GQA: the cache holds kv_heads (half the memory
    here), and teacher-forced decode logits equal the full forward."""
    import numpy as np

    from kubeflow_tpu.models.transformer import init_kv_cache

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, causal=True, attn_impl="reference", dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))[
        "params"
    ]
    B, S, P, MAX = 2, 12, 7, 16
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, 64)
    full = model.apply({"params": params}, toks)
    cache = init_kv_cache(cfg, B, MAX)
    assert next(iter(cache.values()))["k"].shape[1] == 2  # kv_heads, not 4
    lg, cache = model.apply(
        {"params": params}, toks[:, :P], cache=cache, cache_index=0
    )
    np.testing.assert_allclose(
        np.asarray(lg), np.asarray(full[:, :P]), rtol=2e-5, atol=1e-5
    )
    for t in range(P, S):
        kv_mask = jnp.broadcast_to(jnp.arange(MAX) <= t, (B, MAX))
        lg, cache = model.apply(
            {"params": params}, toks[:, t:t + 1],
            cache=cache, cache_index=t, kv_mask=kv_mask,
        )
        np.testing.assert_allclose(
            np.asarray(lg[:, 0]), np.asarray(full[:, t]),
            rtol=2e-5, atol=1e-5, err_msg=f"gqa decode step {t}",
        )


def test_sliding_window_model_flash_matches_reference():
    """attn_window at the model level: flash and reference agree, and the
    window genuinely restricts attention (differs from full causal)."""
    import numpy as np

    kw = dict(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        causal=True, attn_window=16, attn_block_q=16, attn_block_k=16,
        interpret_kernels=True, dtype=jnp.float32,
    )
    cfg_f = TransformerConfig(attn_impl="flash", **kw)
    cfg_r = TransformerConfig(attn_impl="reference", **kw)
    model_f, model_r = TransformerLM(cfg_f), TransformerLM(cfg_r)
    params = model_r.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 64)
    out_f = model_f.apply({"params": params}, toks)
    out_r = model_r.apply({"params": params}, toks)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_r), rtol=2e-4, atol=2e-4
    )
    cfg_full = TransformerConfig(
        attn_impl="reference", **{**kw, "attn_window": None}
    )
    out_full = TransformerLM(cfg_full).apply({"params": params}, toks)
    assert not np.allclose(np.asarray(out_r), np.asarray(out_full))


def test_sliding_window_config_validation():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="causal"):
        TransformerConfig(causal=False, attn_window=8).validate()
    with _pytest.raises(ValueError, match="context parallelism"):
        TransformerConfig(attn_impl="ring", attn_window=8).validate()


@pytest.mark.slow
def test_remat_policies_preserve_loss_and_grads(devices8):
    """remat and remat_policy='dots' trade memory for recompute — they
    must change NOTHING numerically (same loss, same grads)."""
    import optax

    def make(remat, policy):
        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
            attn_impl="reference", dtype=jnp.float32,
            remat=remat, remat_policy=policy,
        )
        return TransformerLM(cfg)

    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
    tgts = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    base = make(False, None)
    params = base.init(jax.random.PRNGKey(2), toks)["params"]

    def loss_fn(model):
        def f(p):
            lg = model.apply({"params": p}, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                lg, tgts
            ).mean()
        return f

    l0, g0 = jax.value_and_grad(loss_fn(base))(params)
    for remat, policy in ((True, None), (True, "dots")):
        l1, g1 = jax.value_and_grad(loss_fn(make(remat, policy)))(params)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            g0, g1,
        )
    with pytest.raises(ValueError, match="remat_policy"):
        TransformerConfig(remat=True, remat_policy="bogus").validate()
    with pytest.raises(ValueError, match="requires remat=True"):
        TransformerConfig(remat=False, remat_policy="dots").validate()
