"""Flagship transformer: impl equivalence across parallel strategies,
sharded training with FSDP+TP rules, MoE variant, remat."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.core.mesh import Axis, MeshSpec, build_mesh
from kubeflow_tpu.data.synthetic import TokenLMDataset, local_shard_iterator
from kubeflow_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    make_init_fn,
    make_loss_fn,
)
from kubeflow_tpu.parallel.expert import MoEConfig
from kubeflow_tpu.parallel.sharding import transformer_rules
from kubeflow_tpu.train.loop import TrainConfig, Trainer

VOCAB, SEQ, DM, HEADS = 128, 256, 64, 8


def _cfg(**kw):
    base = dict(
        vocab_size=VOCAB,
        d_model=DM,
        n_layers=2,
        n_heads=HEADS,
        d_ff=128,
        attn_impl="reference",
        interpret_kernels=True,
    )
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def tokens():
    return jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, (4, SEQ)), jnp.int32
    )


@pytest.fixture(scope="module")
def ref_setup(tokens):
    cfg = _cfg()
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    return params, logits


def test_forward_shape_and_finite(ref_setup, tokens):
    _, logits = ref_setup
    assert logits.shape == (4, SEQ, VOCAB)
    assert bool(jnp.isfinite(logits).all())


@pytest.mark.parametrize(
    "impl,mesh_kw",
    [
        ("flash", {}),                       # no mesh: direct pallas call
        ("flash", {"data": 2, "model": 4}),  # TP head sharding via shard_map
        ("ring", {"data": 2, "seq": 4}),     # context parallel
        ("ulysses", {"seq": 8}),             # sequence parallel
    ],
)
def test_attention_impls_match_reference(ref_setup, tokens, devices8, impl, mesh_kw):
    params, ref_logits = ref_setup
    cfg = _cfg(attn_impl=impl)
    model = TransformerLM(cfg)
    if mesh_kw:
        mesh = build_mesh(MeshSpec(**mesh_kw))
        with jax.set_mesh(mesh):
            logits = jax.jit(
                lambda p, t: model.apply({"params": p}, t)
            )(params, tokens)
    else:
        logits = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), atol=2e-3,
        err_msg=f"{impl} vs reference ({mesh_kw})",
    )


def test_flash_rejects_seq_sharding(ref_setup, tokens, devices8):
    params, _ = ref_setup
    model = TransformerLM(_cfg(attn_impl="flash"))
    mesh = build_mesh(MeshSpec(seq=8))
    with jax.set_mesh(mesh):
        with pytest.raises(ValueError, match="ring|ulysses"):
            jax.jit(lambda p, t: model.apply({"params": p}, t))(params, tokens)


def _train(cfg_model, mesh_spec, steps=6, rules=None, seq=64, batch=16):
    model = TransformerLM(cfg_model)
    trainer = Trainer(
        init_params=make_init_fn(model, seq, mesh_spec.batch_partitions),
        loss_fn=make_loss_fn(model),
        optimizer=optax.adam(1e-2),
        config=TrainConfig(
            mesh=mesh_spec, global_batch=batch, steps=steps, log_every=2
        ),
        param_spec_fn=rules,
    )
    ds = TokenLMDataset(vocab_size=cfg_model.vocab_size, seq_len=seq)
    state, history = trainer.fit(
        lambda s: local_shard_iterator(ds, batch, start_step=s)
    )
    return trainer, state, history


def test_train_fsdp_tp_sharded(devices8):
    cfg = _cfg(n_layers=2, attn_impl="flash")
    rules = transformer_rules()
    trainer, state, history = _train(cfg, MeshSpec(data=2, fsdp=2, model=2), rules=rules)
    assert history[-1]["loss"] < history[0]["loss"]
    # check a TP param really is sharded over model and fsdp
    q = state.params["layers_0"]["attn"]["q_proj"]["kernel"]
    spec = q.sharding.spec
    assert spec == (Axis.FSDP, Axis.MODEL), spec
    # optimizer moments colocated with params
    mu_q = state.opt_state[0].mu["layers_0"]["attn"]["q_proj"]["kernel"]
    assert mu_q.sharding.spec == q.sharding.spec


def test_train_ring_attention_long_context(devices8):
    cfg = _cfg(n_layers=1, attn_impl="ring", attn_block_q=64, attn_block_k=64)
    _, _, history = _train(cfg, MeshSpec(data=2, seq=4), seq=256)
    assert history[-1]["loss"] < history[0]["loss"]


def test_train_moe_expert_parallel(devices8):
    cfg = _cfg(
        n_layers=2,
        attn_impl="reference",
        moe_every=2,
        moe=MoEConfig(num_experts=4, expert_dim=64, top_k=2),
    )
    trainer, state, history = _train(
        cfg, MeshSpec(data=2, expert=4), rules=transformer_rules()
    )
    assert history[-1]["loss"] < history[0]["loss"]
    assert "moe_aux" in history[0]
    up = state.params["layers_1"]["experts"]["up_kernel"]
    assert up.sharding.spec[0] == Axis.EXPERT


def test_remat_matches(ref_setup, tokens):
    params, ref_logits = ref_setup
    model = TransformerLM(_cfg(remat=True))
    logits = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), atol=1e-5
    )


def test_bidirectional_encoder_mode(tokens):
    cfg = _cfg(causal=False, use_rope=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    assert "pos_embedding" in params
    logits = model.apply({"params": params}, tokens)
    # bidirectional: flipping future tokens must change position-0 logits
    toks2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % VOCAB)
    logits2 = model.apply({"params": params}, toks2)
    assert not np.allclose(np.asarray(logits[:, 0]), np.asarray(logits2[:, 0]))


def test_embed_onehot_matches_gather(ref_setup, tokens):
    # same params, same numbers — onehot is the SPMD-clean lookup form
    params, ref_logits = ref_setup
    model = TransformerLM(_cfg(embed_impl="onehot"))
    logits = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), atol=1e-5
    )
