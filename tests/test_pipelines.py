"""Pipelines plane tests: compiler goldens, DAG executor, cache,
lineage, JAXJob steps, recurring runs.

Mirrors the reference's test tiers (SURVEY.md §4): KFP compiler golden
tests diff compiled IR; executor/caching logic is unit-tested without a
cluster; the JAXJob-step path is the kind-e2e analog on a LocalCluster.
"""

from __future__ import annotations

import json
import time

import pytest

from kubeflow_tpu.pipelines import (
    ArtifactStore,
    Dataset,
    Input,
    LineageStore,
    Metrics,
    Output,
    PipelineIR,
    PipelineRunner,
    RecurringRun,
    RunScheduler,
    StepCache,
    compile_pipeline,
    component,
    pipeline,
)


# --------------------------------------------------------------------- #
# components used throughout


@component
def make_data(n: int, out: Output[Dataset]) -> None:
    with open(out.path, "w") as f:
        f.write(",".join(str(i) for i in range(n)))
    out.metadata["rows"] = n


@component
def total(data: Input[Dataset]) -> int:
    with open(data.path) as f:
        return sum(int(x) for x in f.read().split(","))


@component
def add(a: int, b: int) -> int:
    return a + b


@component
def report(value: int, metrics: Output[Metrics]) -> None:
    metrics.log_metric("value", float(value))


@pipeline(name="sum-pipeline", description="make → total → add → report")
def sum_pipeline(n: int = 10, offset: int = 5):
    d = make_data(n=n)
    t = total(data=d.output)
    s = add(a=t.output, b=offset)
    report(value=s.output)


@pytest.fixture()
def runner(tmp_path):
    return PipelineRunner(
        artifact_store=ArtifactStore(str(tmp_path / "artifacts")),
        cache=StepCache(str(tmp_path / "cache")),
        lineage=LineageStore(str(tmp_path / "mlmd.db")),
        max_parallel=4,
    )


# --------------------------------------------------------------------- #
# compiler


class TestCompiler:
    def test_ir_structure(self):
        ir = compile_pipeline(sum_pipeline)
        assert ir.name == "sum-pipeline"
        assert [t.name for t in ir.tasks] == [
            "make-data", "total", "add", "report"]
        assert dict(ir.parameters) == {"n": 10, "offset": 5}
        add_task = ir.task("add")
        assert dict(add_task.inputs)["a"].task_output == ("total", "Output")
        assert dict(add_task.inputs)["b"].parameter == "offset"

    def test_golden_roundtrip(self):
        """§4 compiler-golden analog: IR serializes deterministically and
        round-trips losslessly."""
        ir = compile_pipeline(sum_pipeline)
        js = ir.to_json()
        assert js == compile_pipeline(sum_pipeline).to_json()  # deterministic
        back = PipelineIR.from_json(js)
        assert back.to_json() == js
        assert json.loads(js)["schemaVersion"] == "kft/v1"

    def test_topological_order(self):
        ir = compile_pipeline(sum_pipeline)
        waves = ir.topological_order()
        flat = [t for w in waves for t in w]
        assert flat.index("make-data") < flat.index("total") < flat.index("add")

    def test_duplicate_invocations_get_unique_names(self):
        @pipeline
        def twice():
            add(a=1, b=2)
            add(a=3, b=4)

        ir = compile_pipeline(twice)
        assert [t.name for t in ir.tasks] == ["add", "add-2"]

    def test_cycle_rejected_via_after(self):
        @pipeline
        def cyclic():
            x = add(a=1, b=2)
            y = add(a=3, b=4)
            x.after(y)
            y.after(x)

        with pytest.raises(ValueError, match="cycle"):
            compile_pipeline(cyclic)

    def test_passing_task_not_output_is_an_error(self):
        @pipeline
        def bad():
            x = add(a=1, b=2)
            add(a=x, b=1)

        with pytest.raises(TypeError, match="pass `.output`"):
            compile_pipeline(bad)

    def test_component_plain_call_outside_pipeline(self):
        assert add(a=2, b=3) == 5

    def test_conflicting_component_names_rejected(self):
        @component(name="same")
        def one(a: int) -> int:
            return a * 2

        @component(name="same")
        def two(a: int) -> int:
            return a * 100

        @pipeline
        def p():
            one(a=1)
            two(a=1)

        with pytest.raises(ValueError, match="both named 'same'"):
            compile_pipeline(p)

    def test_multiline_decorator_source_is_executable(self, runner):
        @component(
            name="ml-deco",
        )
        def g(a: int) -> int:
            return a + 1

        @pipeline
        def p():
            g(a=41)

        ir = compile_pipeline(p)
        assert ir.component("ml-deco").source.startswith("def g")
        res = runner.run(ir, {})
        assert res.state == "SUCCEEDED", res.tasks["ml-deco"].error
        assert res.output("ml-deco") == 42

    def test_none_is_a_valid_parameter_default(self, runner):
        @component
        def echo(tag: str) -> str:
            return str(tag)

        @pipeline
        def p(tag: str = None):  # noqa: RUF013 — None default is intended
            echo(tag=tag)

        res = runner.run(compile_pipeline(p), {})
        assert res.state == "SUCCEEDED"
        assert res.output("echo") == "None"

    def test_required_parameter_must_be_supplied(self, runner):
        @pipeline
        def p(n: int):
            add(a=n, b=1)

        with pytest.raises(ValueError, match="without values"):
            runner.run(compile_pipeline(p), {})


# --------------------------------------------------------------------- #
# executor / runner


class TestRunner:
    def test_end_to_end(self, runner):
        ir = compile_pipeline(sum_pipeline)
        result = runner.run(ir, {"n": 4})
        assert result.state == "SUCCEEDED"
        assert result.output("total") == 0 + 1 + 2 + 3
        assert result.output("add") == 6 + 5
        art = result.output("make-data", "out")
        assert isinstance(art, Dataset)
        assert art.metadata["rows"] == 4
        metrics = result.output("report", "metrics")
        assert metrics.metadata["value"] == 11.0

    def test_parameter_override_and_unknown_param(self, runner):
        ir = compile_pipeline(sum_pipeline)
        res = runner.run(ir, {"n": 3, "offset": 100})
        assert res.output("add") == 3 + 100
        with pytest.raises(KeyError):
            runner.run(ir, {"nope": 1})

    def test_failure_skips_downstream(self, runner):
        @component
        def boom() -> int:
            raise RuntimeError("kaboom")

        @pipeline
        def failing():
            b = boom()
            add(a=b.output, b=1)

        res = runner.run(compile_pipeline(failing), {})
        assert res.state == "FAILED"
        assert res.tasks["boom"].state == "FAILED"
        assert "kaboom" in res.tasks["boom"].error
        assert res.tasks["add"].state == "SKIPPED"

    def test_retries(self, runner, tmp_path):
        marker = tmp_path / "flaky-marker"

        @component
        def flaky(path: str) -> int:
            import os
            if not os.path.exists(path):
                open(path, "w").close()
                raise RuntimeError("first attempt fails")
            return 42

        @pipeline
        def p():
            flaky(path=str(marker)).set_retry(2)

        res = runner.run(compile_pipeline(p), {})
        assert res.state == "SUCCEEDED"
        assert res.tasks["flaky"].attempts == 2

    def test_independent_tasks_run_concurrently(self, runner):
        @component
        def sleeper(ms: int) -> int:
            import time as _t
            _t.sleep(ms / 1000)
            return ms

        @pipeline
        def fanout():
            for _ in range(4):
                sleeper(ms=300).set_caching_options(False)

        t0 = time.monotonic()
        res = runner.run(compile_pipeline(fanout), {})
        assert res.state == "SUCCEEDED"
        assert time.monotonic() - t0 < 1.0   # 4×300ms serial would be 1.2s


class TestCache:
    def test_cache_hit_on_rerun(self, runner):
        ir = compile_pipeline(sum_pipeline)
        r1 = runner.run(ir, {"n": 4})
        r2 = runner.run(ir, {"n": 4})
        assert all(not t.cache_hit for t in r1.tasks.values())
        assert all(t.cache_hit for t in r2.tasks.values())
        assert r2.output("add") == r1.output("add")

    def test_param_change_busts_cache(self, runner):
        ir = compile_pipeline(sum_pipeline)
        runner.run(ir, {"n": 4})
        r2 = runner.run(ir, {"n": 5})
        assert not r2.tasks["make-data"].cache_hit
        assert r2.output("total") == 10

    def test_caching_can_be_disabled(self, runner):
        @pipeline
        def p():
            add(a=1, b=2).set_caching_options(False)

        ir = compile_pipeline(p)
        runner.run(ir, {})
        r2 = runner.run(ir, {})
        assert not r2.tasks["add"].cache_hit


class TestLineage:
    def test_executions_and_artifacts_recorded(self, runner):
        ir = compile_pipeline(sum_pipeline)
        res = runner.run(ir, {"n": 4})
        execs = runner.lineage.executions(res.run_id)
        assert [e["task"] for e in execs] == [
            "make-data", "total", "add", "report"]
        assert all(e["state"] == "SUCCEEDED" for e in execs)
        made = runner.lineage.artifacts_of(execs[0]["id"], "output")
        assert made[0]["type"] == "system.Dataset"
        # the dataset's lineage shows producer + consumer
        lin = runner.lineage.lineage(made[0]["uri"])
        assert {(x["task"], x["direction"]) for x in lin} == {
            ("make-data", "output"), ("total", "input")}


# --------------------------------------------------------------------- #
# JAXJob-backed steps (§3.5 mapping) — kind-e2e analog


class TestJobSteps:
    def test_tpu_step_runs_as_gang_job(self, tmp_path):
        from kubeflow_tpu.orchestrator.cluster import LocalCluster
        from kubeflow_tpu.orchestrator.resources import Fleet

        @component
        def devcount() -> int:
            import os
            return int(os.environ.get("JAX_NUM_PROCESSES", "0"))

        @pipeline
        def p():
            devcount().set_tpu_request(chips=1, num_workers=2)

        with LocalCluster(fleet=Fleet.homogeneous(2, "2x2"),
                          base_dir=str(tmp_path / "cluster"),
                          resync_period=0.05) as cluster:
            runner = PipelineRunner(
                artifact_store=ArtifactStore(str(tmp_path / "artifacts")),
                cluster=cluster,
                job_timeout_s=60.0,
            )
            res = runner.run(compile_pipeline(p), {})
        assert res.state == "SUCCEEDED", res.tasks["devcount"].error
        assert res.output("devcount") == 2   # gang wiring reached the step


# --------------------------------------------------------------------- #
# recurring runs


class TestScheduler:
    def test_recurring_fires_and_stops_at_max(self, runner):
        @pipeline
        def tick():
            add(a=1, b=1).set_caching_options(False)

        ir = compile_pipeline(tick)
        rr = RecurringRun(pipeline=ir, interval_s=0.1, max_runs=2)
        with RunScheduler(runner) as sched:
            sched.add(rr)
            deadline = time.monotonic() + 10
            while rr.fired < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            time.sleep(0.3)   # would fire again if max_runs were ignored
        assert rr.fired == 2
        assert len(rr.history) == 2
        assert all(h.state == "SUCCEEDED" for h in rr.history)

    def test_slow_schedule_does_not_starve_others(self, runner):
        @component
        def slow() -> int:
            import time as _t
            _t.sleep(0.5)
            return 1

        @component
        def quick() -> int:
            return 2

        @pipeline
        def slow_p():
            slow().set_caching_options(False)

        @pipeline
        def quick_p():
            quick().set_caching_options(False)

        a = RecurringRun(pipeline=compile_pipeline(slow_p), interval_s=0.05)
        b = RecurringRun(pipeline=compile_pipeline(quick_p), interval_s=0.05)
        with RunScheduler(runner) as sched:
            sched.add(a)
            sched.add(b)
            deadline = time.monotonic() + 10
            while b.fired < 4 and time.monotonic() < deadline:
                time.sleep(0.02)
        # quick schedule kept firing while the slow run was inflight
        assert b.fired >= 4
        # and the slow schedule never overlapped itself
        assert a.fired <= 3

    def test_pause_resume(self, runner):
        @pipeline
        def tick():
            add(a=2, b=2).set_caching_options(False)

        rr = RecurringRun(pipeline=compile_pipeline(tick), interval_s=0.05)
        with RunScheduler(runner) as sched:
            uid = sched.add(rr)
            deadline = time.monotonic() + 10
            while rr.fired < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            sched.pause(uid)
            fired = rr.fired
            time.sleep(0.2)
            assert rr.fired == fired   # paused: no new fires
            sched.resume(uid)
            deadline = time.monotonic() + 10
            while rr.fired == fired and time.monotonic() < deadline:
                time.sleep(0.02)
            assert rr.fired > fired
