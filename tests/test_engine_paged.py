"""Paged KV cache (serve/paging.py + engine paged mode): the vLLM
block-table analog. The invariant everywhere: PAGING IS A LAYOUT, NOT A
NUMERICS CHANGE — every completion must equal the dense engine's (which
is itself pinned to the whole-batch generate path), while HBM is billed
per resident token instead of per (row × max_seq) rectangle."""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM
from kubeflow_tpu.serve.engine import LMEngine
from kubeflow_tpu.serve.paging import PageAllocator

CFG = TransformerConfig(
    vocab_size=89, d_model=32, n_layers=2, n_heads=4, d_ff=64,
    causal=True, max_seq_len=256, attn_impl="reference", dtype=jnp.float32,
)
EOS = 1


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return model, params


def _prompts(rng, n, lo=3, hi=25, vocab=89):
    return [
        [int(x) for x in rng.integers(2, vocab, size=rng.integers(lo, hi))]
        for _ in range(n)
    ]


# ---------------------------------------------------------------- allocator


def test_allocator_accounting():
    a = PageAllocator(
        pool_tokens=16 * 8, page_size=16, max_batch=4, max_pages_per_row=4
    )
    assert a.pages_for(1) == 1 and a.pages_for(16) == 1 and a.pages_for(17) == 2
    assert a.free_pages == 7  # page 0 is scratch
    a.alloc(0, 3)
    a.alloc(1, 4)
    assert a.used_pages == 7 and not a.can_alloc(1)
    # tables point at distinct non-scratch pages; unused entries at scratch
    assert len(set(a.table[0, :3]) | set(a.table[1])) == 7
    assert 0 not in a.table[0, :3] and a.table[0, 3] == 0
    with pytest.raises(RuntimeError, match="exhausted"):
        a.alloc(2, 1)
    with pytest.raises(RuntimeError, match="already holds"):
        a.alloc(0, 1)
    a.free(0)
    assert a.free_pages == 3 and np.all(a.table[0] == 0)
    a.free(0)  # idempotent
    with pytest.raises(ValueError, match="max_pages_per_row"):
        a.alloc(2, 5)
    with pytest.raises(ValueError, match="16-multiple"):
        PageAllocator(pool_tokens=64, page_size=10, max_batch=1,
                      max_pages_per_row=1)


def test_device_table_memo_evicts_stale_widths():
    """The device-mirror memo holds at most one entry per width, all
    from the CURRENT table version — a long-lived engine with churning
    horizons must not pin one stale int32 slab per width it ever
    touched."""
    a = PageAllocator(
        pool_tokens=16 * 8, page_size=16, max_batch=4, max_pages_per_row=4
    )
    a.alloc(0, 2)
    a.device_table(2)
    a.device_table(4)
    assert len(a._dev) == 2 and a.device_uploads == 2
    a.alloc(1, 2)  # version bump → both memo entries are now stale
    a.device_table(4)  # miss: evicts the stale pair, uploads one fresh
    assert len(a._dev) == 1 and a.device_uploads == 3
    assert all(ver == a.version for ver, _ in a._dev.values())
    a.device_table(2)
    assert len(a._dev) == 2 and a.device_uploads == 4
    a.device_table(2)  # hit: no upload, no eviction
    assert len(a._dev) == 2 and a.device_uploads == 4


# ------------------------------------------------------------------ parity


def _dense_and_paged(model, params, *, prefix=0, chunked=None, cfg=CFG,
                     pool_tokens=16 * 20, max_batch=4):
    dense = LMEngine(
        model, cfg, params, max_batch=max_batch, max_seq=64, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS, prefix_cache_entries=prefix,
        prefill_chunk=chunked,
    ).start()
    paged = LMEngine(
        model, cfg, params, max_batch=max_batch, max_seq=64, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS, prefix_cache_entries=prefix,
        prefill_chunk=chunked, kv_pool_tokens=pool_tokens, page_size=16,
    ).start()
    return dense, paged


def test_paged_matches_dense_exactly(model_and_params):
    model, params = model_and_params
    dense, paged = _dense_and_paged(model, params)
    try:
        rng = np.random.default_rng(0)
        for ids in _prompts(rng, 8):
            want = dense.submit(ids, max_new_tokens=12)
            got = paged.submit(ids, max_new_tokens=12)
            assert got == want, (ids, got, want)
        assert paged.pager.used_pages == 0  # everything freed
    finally:
        dense.stop()
        paged.stop()


def test_paged_concurrent_staggered(model_and_params):
    """Continuous batching on the paged cache: staggered arrivals share
    the running batch and still match the dense engine."""
    model, params = model_and_params
    dense, paged = _dense_and_paged(model, params, max_batch=3)
    rng = np.random.default_rng(1)
    prompts = _prompts(rng, 7)
    want = {}
    results: dict[int, list[int]] = {}
    errors: list[Exception] = []

    def worker(i):
        try:
            time.sleep(0.03 * i)
            results[i] = paged.submit(prompts[i], max_new_tokens=16)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(7)]
    try:
        for i, ids in enumerate(prompts):
            want[i] = dense.submit(ids, max_new_tokens=16)
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
    finally:
        dense.stop()
        paged.stop()
    assert not errors, errors
    assert results == want
    assert paged.stats["max_concurrent"] >= 2


def test_paged_prefix_cache_parity_and_reuse(model_and_params):
    """Automatic prefix caching on the paged cache: exact same tokens,
    real reuse, and the stored-entry format interchangeable with dense
    mode (extract gathers through the table, implant scatters)."""
    model, params = model_and_params
    dense, paged = _dense_and_paged(model, params, prefix=4)
    try:
        shared = [7] * 20
        tails = [[11, 12], [13, 14, 15], [16]]
        for tail in tails:
            want = dense.submit(shared + tail, max_new_tokens=10)
            got = paged.submit(shared + tail, max_new_tokens=10)
            assert got == want, (tail, got, want)
        assert paged.stats["prefix_hits"] >= 2
        assert paged.stats["prefix_tokens_reused"] >= 32
    finally:
        dense.stop()
        paged.stop()


def test_paged_chunked_prefill_parity(model_and_params):
    model, params = model_and_params
    dense, paged = _dense_and_paged(model, params, chunked=16,
                                    pool_tokens=16 * 24)
    try:
        rng = np.random.default_rng(3)
        for ids in _prompts(rng, 4, lo=20, hi=45):
            want = dense.submit(ids, max_new_tokens=8)
            got = paged.submit(ids, max_new_tokens=8)
            assert got == want, (len(ids), got, want)
        assert paged.stats["prefill_pieces"] > 4  # really chunked
    finally:
        dense.stop()
        paged.stop()


def test_paged_sliding_window_and_gqa(model_and_params):
    """Window + GQA ride the paged branch's position-space mask."""
    import dataclasses

    for variant in (
        dataclasses.replace(CFG, attn_window=4),
        dataclasses.replace(CFG, n_kv_heads=2),
    ):
        model = TransformerLM(variant)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        dense, paged = _dense_and_paged(model, params, cfg=variant)
        try:
            rng = np.random.default_rng(5)
            for ids in _prompts(rng, 4, lo=6, hi=20):
                want = dense.submit(ids, max_new_tokens=10)
                got = paged.submit(ids, max_new_tokens=10)
                assert got == want, (variant.attn_window, got, want)
        finally:
            dense.stop()
            paged.stop()


# ------------------------------------------------------- density/backpressure


def test_page_backpressure_queues_and_completes(model_and_params):
    """A pool too small for all concurrent requests must QUEUE the
    overflow (FIFO, no failure) and finish everything as pages free."""
    model, params = model_and_params
    # 8 pages of 16 = 128 tokens; each request needs (20 + 12)/16 -> 2
    # pages, so only 3-4 of the 8 requests fit at once
    eng = LMEngine(
        model, CFG, params, max_batch=8, max_seq=64, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS,
        kv_pool_tokens=16 * 9, page_size=16,
    ).start()
    ref = LMEngine(
        model, CFG, params, max_batch=8, max_seq=64, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    rng = np.random.default_rng(7)
    prompts = _prompts(rng, 8, lo=17, hi=21)
    results: dict[int, list[int]] = {}
    errors: list[Exception] = []

    def worker(i):
        try:
            results[i] = eng.submit(prompts[i], max_new_tokens=12,
                                    timeout_s=120)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(150)
        assert not errors, errors
        assert len(results) == 8
        for i, ids in enumerate(prompts):
            assert results[i] == ref.submit(ids, max_new_tokens=12), i
        # the pool bound really bit: peak pages within budget, and fewer
        # rows ran concurrently than max_batch allows
        assert eng.stats["kv_pages_used_peak"] <= 8
        assert eng.stats["max_concurrent"] <= 4
    finally:
        eng.stop()
        ref.stop()


def test_paged_density_vs_dense_rectangle(model_and_params):
    """The point of paging: mixed-length rows resident in a pool ~3.6x
    smaller than the dense rectangle. 8 concurrent rows of <=48 tokens
    each fit in 576 pool tokens (9 pages: 8 allocatable + scratch) where
    dense billing would need 8 x 256 = 2048 — >=2x density in the same
    HBM budget."""
    model, params = model_and_params
    max_seq = 256
    pool_tokens = 64 * 9
    dense_rectangle = 8 * max_seq
    assert dense_rectangle / pool_tokens >= 2  # the VERDICT bar, by design
    eng = LMEngine(
        model, CFG, params, max_batch=8, max_seq=max_seq, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS,
        kv_pool_tokens=pool_tokens, page_size=64,
    ).start()
    rng = np.random.default_rng(11)
    prompts = _prompts(rng, 8, lo=10, hi=30)
    results: dict[int, list[int]] = {}
    errors: list[Exception] = []

    def worker(i):
        try:
            results[i] = eng.submit(prompts[i], max_new_tokens=16,
                                    timeout_s=120)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(150)
        assert not errors, errors
        # ALL 8 mixed-length rows were resident simultaneously in a pool
        # 4x smaller than their dense rectangle
        assert eng.stats["max_concurrent"] == 8
        assert eng.stats["kv_pages_used_peak"] * 64 <= pool_tokens
    finally:
        eng.stop()


def test_request_larger_than_pool_fails_fast(model_and_params):
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=128, chunk_steps=2,
        prefill_buckets=(32, 128), eos_id=EOS,
        kv_pool_tokens=16 * 4, page_size=16,
    ).start()
    try:
        with pytest.raises(ValueError, match="raise kv_pool_tokens"):
            eng.submit(list(range(2, 60)), max_new_tokens=32)
        # a fitting request still completes after the rejection (this tiny
        # model may emit EOS immediately — liveness is what's asserted)
        eng.submit([5, 6, 7], max_new_tokens=4)
        assert eng.stats["completed"] == 1 and eng._fatal is None
    finally:
        eng.stop()


def test_tp_paged_engine_matches_unsharded():
    """TP serving + paged cache compose: pooled KV sharded over kv heads
    on the model axis, same tokens as the unsharded dense engine."""
    from jax.sharding import Mesh

    from kubeflow_tpu.parallel.sharding import transformer_rules

    cfg = TransformerConfig(
        vocab_size=96, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        causal=True, max_seq_len=256, attn_impl="reference",
        dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    devs = np.asarray(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("data", "model"))

    plain = LMEngine(
        model, cfg, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    sharded = LMEngine(
        model, cfg, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
        mesh=mesh, rules=transformer_rules(fsdp=False),
        kv_pool_tokens=16 * 16, page_size=16,
    ).start()
    try:
        k0 = next(iter(sharded.cache.values()))["k"]
        assert "model" in str(k0.sharding.spec)
        rng = np.random.default_rng(31)
        for _ in range(3):
            ids = [int(x) for x in rng.integers(2, 96, size=rng.integers(4, 20))]
            a = plain.submit(ids, max_new_tokens=10)
            b = sharded.submit(ids, max_new_tokens=10)
            assert a == b, (ids, a, b)
    finally:
        plain.stop()
        sharded.stop()


def test_paged_engine_exports_pool_gauges():
    """/metrics on an engine-backed server shows the paged pool's live
    pressure (pages_total/pages_used) next to the scheduler gauges."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer

    m = LMEngineModel(
        "plm", None, config=CFG, max_batch=2, max_seq=64, chunk_steps=4,
        max_new_tokens=6, eos_id=EOS,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        kv_pool_tokens=16 * 8, page_size=16,
    )
    server = ModelServer([m])

    async def run():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post(
                "/v1/models/plm:predict",
                json={"instances": [{"input_ids": [5, 6, 7]}]},
            )
            assert r.status == 200
            text = await (await client.get("/metrics")).text()
            assert 'kubeflow_tpu_engine_kv_pages_total{model="plm"} 7' in text
            assert 'kubeflow_tpu_engine_kv_pages_used{model="plm"}' in text
            assert 'kubeflow_tpu_engine_kv_pages_used_peak{model="plm"}' in text

    try:
        asyncio.run(run())
    finally:
        m.unload()


# ----------------------------------------------- pipelined decode (carry)


def test_paged_pipelined_parity_across_horizon_growth(model_and_params):
    """Pipelined paged decode must stay byte-identical to the inline path
    while the page read window grows ACROSS speculative chunks: a long
    budget walks the pow2 page-window buckets (1 → 2 → 4 pages at
    page_size=16) mid-generation, exercising the in-epoch table widening
    without a full carry re-upload."""
    model, params = model_and_params
    kw = dict(
        max_batch=2, max_seq=64, chunk_steps=4, prefill_buckets=(32,),
        eos_id=EOS, kv_pool_tokens=16 * 12, page_size=16, seed=7,
    )
    rng = np.random.default_rng(61)
    prompts = _prompts(rng, 3, lo=4, hi=11)
    outs: dict[int, list[list[int]]] = {}
    for depth in (0, 1):
        eng = LMEngine(model, CFG, params, pipeline_depth=depth, **kw).start()
        try:
            outs[depth] = [
                eng.submit(p, max_new_tokens=40) for p in prompts
            ]
            if depth == 1:
                # widenings are log-bounded table uploads, never per-chunk
                assert (
                    eng.overlap["carry_uploads"] < eng.stats["chunks"]
                ), (eng.overlap["carry_uploads"], eng.stats["chunks"])
        finally:
            eng.stop()
    assert outs[0] == outs[1], (outs[0], outs[1])
    assert any(len(o) > 0 for o in outs[1])


def test_paged_pipelined_concurrent_with_backpressure(model_and_params):
    """Pipelined paged mode under page backpressure (held admissions) and
    concurrent mixed-length traffic: answers equal the inline engine's,
    and the pool frees fully afterwards — a speculative chunk must never
    leak pages of a retired row."""
    model, params = model_and_params
    kw = dict(
        max_batch=3, max_seq=64, chunk_steps=4, prefill_buckets=(32,),
        eos_id=EOS, kv_pool_tokens=16 * 7, page_size=16, seed=3,
    )
    rng = np.random.default_rng(67)
    prompts = _prompts(rng, 6, lo=3, hi=14)

    def run_mode(depth):
        eng = LMEngine(model, CFG, params, pipeline_depth=depth, **kw).start()
        outs: dict[int, list[int]] = {}
        errors: list[Exception] = []

        def worker(i):
            try:
                time.sleep(0.015 * i)
                outs[i] = eng.submit(prompts[i], max_new_tokens=10)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        try:
            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(180)
            assert not errors, errors
            assert eng.pager.used_pages == 0  # no leaked pages
        finally:
            eng.stop()
        return outs

    pipe = run_mode(1)
    inline = run_mode(0)
    for i in range(len(prompts)):
        assert pipe[i] == inline[i], (i, pipe[i], inline[i])


# ----------------------------------------------- speculative decoding (spec)


def test_paged_spec_parity_across_horizon_growth(model_and_params):
    """Speculative paged decode must stay byte-identical to the non-spec
    paged engine while the page read window grows across chunks — the
    horizon bound now grows +K per step (chunk_span), and beyond-budget
    span positions must route to the scratch page, never clamp into the
    row's own pages."""
    model, params = model_and_params
    kw = dict(
        max_batch=2, max_seq=64, chunk_steps=4, prefill_buckets=(32,),
        eos_id=EOS, kv_pool_tokens=16 * 12, page_size=16, seed=7,
    )
    rng = np.random.default_rng(61)
    prompts = _prompts(rng, 3, lo=4, hi=11) + [[5, 6, 7] * 4]
    outs = {}
    for spec in (0, 4):
        for depth in (0, 1):
            eng = LMEngine(
                model, CFG, params, pipeline_depth=depth,
                spec_draft_tokens=spec, **kw
            ).start()
            try:
                outs[(spec, depth)] = [
                    eng.submit(p, max_new_tokens=40) for p in prompts
                ]
                assert eng.pager.used_pages == 0
            finally:
                eng.stop()
    assert outs[(4, 0)] == outs[(0, 0)]
    assert outs[(4, 1)] == outs[(0, 0)]
    assert outs[(0, 1)] == outs[(0, 0)]


def test_paged_spec_concurrent_with_backpressure(model_and_params):
    """Spec + page backpressure (held admissions) + concurrent traffic:
    answers equal the non-spec paged engine's, and the pool frees fully —
    a speculative span must never leak pages of a retired row."""
    model, params = model_and_params
    kw = dict(
        max_batch=3, max_seq=64, chunk_steps=4, prefill_buckets=(32,),
        eos_id=EOS, kv_pool_tokens=16 * 7, page_size=16, seed=3,
    )
    rng = np.random.default_rng(67)
    prompts = _prompts(rng, 6, lo=3, hi=14)

    def run_mode(spec):
        eng = LMEngine(
            model, CFG, params, spec_draft_tokens=spec, **kw
        ).start()
        outs: dict[int, list[int]] = {}
        errors: list[Exception] = []

        def worker(i):
            try:
                time.sleep(0.015 * i)
                outs[i] = eng.submit(prompts[i], max_new_tokens=10)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        try:
            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(180)
            assert not errors, errors
            assert eng.pager.used_pages == 0  # no leaked pages
        finally:
            eng.stop()
        return outs

    assert run_mode(4) == run_mode(0)


def test_paged_spec_temperature_determinism(model_and_params):
    """Seeded rejection sampling on the paged cache: same seed → same
    stream, twice, through fresh engines."""
    model, params = model_and_params

    def run():
        eng = LMEngine(
            model, CFG, params, max_batch=1, max_seq=64, chunk_steps=4,
            prefill_buckets=(32,), eos_id=EOS, kv_pool_tokens=16 * 8,
            page_size=16, seed=11, spec_draft_tokens=4,
        ).start()
        try:
            return eng.submit([7, 8, 9] * 4, max_new_tokens=16,
                              temperature=0.9)
        finally:
            eng.stop()

    a, b = run(), run()
    assert a == b and len(a) > 0
