"""Serving SRE layer (serve/deadline.py, serve/watchdog.py): end-to-end
deadlines at every seam, deadline-aware admission control, priority
shedding under overload, and the engine watchdog's supervised restart.

Determinism contract: watchdog trip tests drive ``tick()`` directly with
an injected clock (no wall-time sleeps decide outcomes); wedge faults use
the engine's pre-chunk hook with explicit release events.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM
from kubeflow_tpu.obs.prom import REGISTRY
from kubeflow_tpu.serve.deadline import (
    DEADLINE_ABS_HEADER,
    DEADLINE_HEADER,
    PRIORITY_HEADER,
    AdmissionShed,
    DeadlineExceeded,
    deadline_from_headers,
    priority_from_headers,
)
from kubeflow_tpu.serve.engine import EngineOverloaded, LMEngine
from kubeflow_tpu.serve.watchdog import (
    EngineRestarting,
    EngineWatchdog,
    WatchdogConfig,
)

CFG = TransformerConfig(
    vocab_size=89,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    causal=True,
    max_seq_len=256,
    attn_impl="reference",
    dtype=jnp.float32,
)
EOS = 1


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return model, params


def _engine(model, params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq", 64)
    kw.setdefault("chunk_steps", 2)
    kw.setdefault("prefill_buckets", (32,))
    kw.setdefault("eos_id", EOS)
    return LMEngine(model, CFG, params, **kw).start()


def _metric(name, **labels):
    m = REGISTRY._metrics.get(name)
    if m is None:
        return 0.0
    child = m._children.get(tuple(sorted(labels.items())))
    return child.value if child else 0.0


# ------------------------------------------------------------- headers


def test_deadline_header_parsing_and_absolute_precedence():
    clock = lambda: 100.0  # noqa: E731
    assert deadline_from_headers(None) is None
    assert deadline_from_headers({}) is None
    assert deadline_from_headers({DEADLINE_HEADER: "junk"}) is None
    got = deadline_from_headers({DEADLINE_HEADER: "1500"}, clock=clock)
    assert got == pytest.approx(101.5)
    # the title-cased spelling HTTP servers hand us parses identically
    got = deadline_from_headers(
        {DEADLINE_HEADER.title(): "1500"}, clock=clock
    )
    assert got == pytest.approx(101.5)
    # a stamped absolute deadline wins over the relative budget
    got = deadline_from_headers(
        {DEADLINE_HEADER: "1500", DEADLINE_ABS_HEADER: "42.5"}, clock=clock
    )
    assert got == pytest.approx(42.5)
    assert priority_from_headers({PRIORITY_HEADER: "7"}) == 7
    assert priority_from_headers({PRIORITY_HEADER: "x"}) == 0
    assert priority_from_headers({}) == 0


# ---------------------------------------------------- deadline seams


def test_stream_deadline_is_end_to_end_not_per_item(model_and_params):
    """The satellite fix: each live-queue wait used to get the FULL
    timeout, so a slow-but-not-dead stream could overrun its budget by
    tokens × timeout. Now one monotonic deadline governs every wait."""
    from kubeflow_tpu.chaos.injectors import slow_decode

    model, params = model_and_params
    # eos_id outside the vocab: the row can never EOS-retire early, so
    # the decode is deterministically budget-length (no timing race)
    eng = _engine(model, params, eos_id=97)
    stop = slow_decode(eng, delay_s=0.15)
    try:
        t0 = time.monotonic()
        deadline = t0 + 0.5
        chunks = 0
        with pytest.raises(TimeoutError):
            for _ in eng.stream(
                [3, 4, 5], max_new_tokens=30, deadline=deadline
            ):
                chunks += 1
        elapsed = time.monotonic() - t0
        # the old bug: 30 tokens / 2-step chunks × 0.5 s/item ≈ 7.5 s.
        # end-to-end accounting fails it at ~the 0.5 s deadline.
        assert elapsed < 3.0, elapsed
    finally:
        stop()
        eng.stop()


def test_queued_past_deadline_never_admitted(model_and_params):
    """A request whose deadline expires while it waits in the admission
    queue is retired there — it must never cost a decode slot."""
    from kubeflow_tpu.chaos.injectors import wedge_engine

    model, params = model_and_params
    eng = _engine(model, params, max_batch=1)
    release = wedge_engine(eng, hold_s=30.0)
    try:
        q0 = _metric("kft_engine_deadline_expired_total", stage="queued")
        # occupy the single row, then wedge the next chunk
        blocker_err: list = []

        def blocker():
            try:
                eng.submit([5, 6, 7], max_new_tokens=30, timeout_s=60)
            except Exception as e:  # noqa: BLE001
                blocker_err.append(e)

        t = threading.Thread(target=blocker, daemon=True)
        t.start()
        # wait until the wedge hook has actually caught the loop
        deadline = time.monotonic() + 10
        while eng._fault_hooks and time.monotonic() < deadline:
            if not eng.busy():
                time.sleep(0.01)
                continue
            break
        time.sleep(0.2)  # let the loop run into the wedge
        admitted0 = eng.stats["admitted"]
        victim_err: list = []

        def victim():
            try:
                eng.submit([8, 9], max_new_tokens=4, timeout_s=0.3)
            except Exception as e:  # noqa: BLE001
                victim_err.append(e)

        tv = threading.Thread(target=victim, daemon=True)
        tv.start()
        time.sleep(0.5)  # victim's deadline passes while queued
        release()
        tv.join(30)
        t.join(60)
        assert victim_err and isinstance(victim_err[0], DeadlineExceeded)
        assert not blocker_err, blocker_err
        # the victim was never admitted: no decode slot consumed
        assert eng.stats["admitted"] == admitted0
        assert eng.stats["deadline_expired_queued"] == 1
        assert _metric(
            "kft_engine_deadline_expired_total", stage="queued"
        ) == q0 + 1
    finally:
        release()
        eng.stop()


def test_mid_decode_deadline_cancelled_at_epoch(model_and_params):
    """A row past its deadline mid-generation is cancelled at the next
    epoch boundary (the PR 6 drain-merge seam): the caller gets
    DeadlineExceeded and the row frees for new work."""
    from kubeflow_tpu.chaos.injectors import slow_decode

    model, params = model_and_params
    # out-of-vocab eos_id: the row cannot EOS-retire early and race the
    # sweep's deadline attribution
    eng = _engine(model, params, max_batch=1, eos_id=97)
    # warm the prefill + chunk compiles FIRST: a cold compile can eat the
    # whole budget while the row is still prefilling (not yet decoding)
    eng.submit([9, 8], max_new_tokens=2, timeout_s=120)
    stop = slow_decode(eng, delay_s=0.1)
    try:
        with pytest.raises(DeadlineExceeded):
            eng.submit(
                [3, 4, 5], max_new_tokens=30,
                deadline=time.monotonic() + 0.4,
            )
        stop()
        # the engine retires the row at the next epoch boundary
        deadline = time.monotonic() + 15
        while (
            eng.stats["deadline_expired_decoding"] == 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert eng.stats["deadline_expired_decoding"] >= 1
        while eng.active.any() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not eng.active.any()
        out = eng.submit([5, 6], max_new_tokens=3, timeout_s=60)
        assert out  # alive after the cancellation
    finally:
        stop()
        eng.stop()


def test_admission_shed_unmeetable_deadline(model_and_params):
    """Admission control sheds a request whose estimated queue wait +
    decode time exceeds its remaining budget — 503 + Retry-After at the
    server, and NO decode slot consumed."""
    model, params = model_and_params
    eng = _engine(model, params)
    try:
        # evidence: 200 ms per 2-token chunk → 32 tokens ≈ 3.2 s
        eng.overlap["decode_gap_ms"] = 200.0
        with pytest.raises(AdmissionShed) as ei:
            eng.submit(
                [3, 4, 5], max_new_tokens=32,
                deadline=time.monotonic() + 0.5,
            )
        assert ei.value.reason == "deadline_unmeetable"
        assert ei.value.retry_after_s >= 1.0
        assert eng.stats["shed_deadline"] == 1
        assert eng.stats["admitted"] == 0
        # a roomy deadline still admits (the estimator is not a gate)
        out = eng.submit([3, 4, 5], max_new_tokens=4, timeout_s=60)
        assert out
    finally:
        eng.stop()


def test_admission_never_sheds_on_cold_ewma(model_and_params):
    """No throughput evidence → no shed: a cold engine admits everything
    rather than guessing clients into 503s."""
    model, params = model_and_params
    eng = _engine(model, params)
    try:
        assert eng.estimate_admission(32) is None
        out = eng.submit(
            [3, 4], max_new_tokens=4, deadline=time.monotonic() + 30
        )
        assert out
    finally:
        eng.stop()


def test_priority_evicts_lowest_queued_under_overload(model_and_params):
    """Sustained overload sheds the lowest-priority QUEUED request to
    admit a higher-priority one; equal/lower priority newcomers still get
    EngineOverloaded."""
    from kubeflow_tpu.chaos.injectors import wedge_engine

    model, params = model_and_params
    eng = _engine(model, params, max_batch=1, max_queue=2)
    release = wedge_engine(eng, hold_s=30.0)
    results: dict[str, Exception | list] = {}

    def bg(key, ids, prio):
        def run():
            try:
                results[key] = eng.submit(
                    ids, max_new_tokens=20, timeout_s=60, priority=prio
                )
            except Exception as e:  # noqa: BLE001
                results[key] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t

    try:
        t1 = bg("active", [5, 6, 7], 0)   # takes the single row
        time.sleep(0.3)                   # loop admits it, then wedges
        t2 = bg("low", [8, 9], 0)         # queued, priority 0
        t3 = bg("mid", [9, 10], 1)        # queued, priority 1 → capacity full
        time.sleep(0.2)
        # a priority-3 newcomer evicts the LOWEST queued (priority 0)
        t4 = bg("high", [11, 12], 3)
        time.sleep(0.3)
        assert isinstance(results.get("low"), AdmissionShed)
        assert results["low"].reason == "priority_evict"
        # equal-priority newcomer has no one below it: bare overload
        with pytest.raises(EngineOverloaded):
            eng.submit([13, 14], max_new_tokens=4, priority=1)
        assert eng.stats["shed_priority"] == 1
        release()
        for t in (t1, t2, t3, t4):
            t.join(60)
        # survivors all completed
        assert isinstance(results["active"], list)
        assert isinstance(results["mid"], list)
        assert isinstance(results["high"], list)
    finally:
        release()
        eng.stop()


def test_batcher_sheds_expired_entries_at_flush():
    """The batcher seam: an entry whose deadline passed while queued is
    failed with DeadlineExceeded and excluded from the handler call."""
    import asyncio

    from kubeflow_tpu.serve.batcher import Batcher, BatcherConfig

    seen: list[list] = []

    async def handler(flat):
        seen.append(list(flat))
        return [x * 2 for x in flat]

    async def run():
        b = Batcher(handler, BatcherConfig(max_batch_size=8,
                                           max_latency_ms=50.0))
        expired = asyncio.ensure_future(
            b.submit([1, 2], deadline=time.monotonic() - 0.01)
        )
        fresh = asyncio.ensure_future(
            b.submit([10], deadline=time.monotonic() + 30)
        )
        with pytest.raises(DeadlineExceeded):
            await expired
        assert await fresh == [20]
        assert seen == [[10]]  # expired instances never reached a forward
        assert b.stats["deadline_shed"] == 1

    asyncio.run(run())


# ------------------------------------------------------------ watchdog


def _loaded_engine_model(model, params, name="lm", **kw):
    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec

    m = LMEngineModel(
        name, None, config=CFG, max_batch=2, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=8, eos_id=EOS, **kw,
    )
    m.load()
    m._params = jax.device_put(params)
    m.engine.stop()
    m.engine = m._make_engine().start()
    return m


def test_watchdog_trips_on_wedged_chunk_and_restarts(model_and_params):
    """Fake-clock trip: a wedged chunk (stale heartbeat + work pending)
    flips readiness, fails the in-flight request with the RETRYABLE
    EngineRestarting, rebuilds the engine, and restores readiness."""
    from kubeflow_tpu.chaos.injectors import wedge_engine

    model, params = model_and_params
    m = _loaded_engine_model(model, params, name="wd-wedge", watchdog=False)
    now = [0.0]
    ready_flips: list[bool] = []

    def on_ready(r):
        ready_flips.append(r)
        m._set_ready(r)

    wd = EngineWatchdog(
        lambda: m.engine, m.restart_engine, on_ready=on_ready,
        config=WatchdogConfig(min_wedge_s=5.0, wedge_factor=8.0),
        clock=lambda: now[0], model_name="wd-wedge",
    )  # no .start(): ticks are driven explicitly, zero wall-clock waits
    t0 = _metric(
        "kft_engine_watchdog_trips_total", model="wd-wedge", reason="wedged"
    )
    r0 = _metric("kft_engine_restarts_total", model="wd-wedge")
    old_engine = m.engine
    release = wedge_engine(old_engine, hold_s=20.0)
    errs: list = []

    def caller():
        try:
            old_engine.submit([3, 4, 5], max_new_tokens=6, timeout_s=60)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=caller, daemon=True)
    t.start()
    try:
        # wait (bounded) for the loop to be demonstrably wedged: work
        # exists and the heartbeat has stopped advancing
        spin = time.monotonic() + 20
        while time.monotonic() < spin:
            beat = old_engine.heartbeat()
            time.sleep(0.1)
            if old_engine.busy() and old_engine.heartbeat() == beat:
                break
        # below threshold: no trip
        now[0] = old_engine.heartbeat() + 1.0
        assert wd.tick() is None
        # past threshold: trip + supervised restart
        now[0] = old_engine.heartbeat() + 10.0
        assert wd.tick() == "wedged"
        assert ready_flips == [False, True]
        assert m.ready is True
        assert m.engine is not old_engine
        t.join(30)
        assert errs and isinstance(errs[0], EngineRestarting)
        assert _metric(
            "kft_engine_watchdog_trips_total", model="wd-wedge",
            reason="wedged",
        ) == t0 + 1
        assert _metric(
            "kft_engine_restarts_total", model="wd-wedge"
        ) == r0 + 1
        assert wd.stats["trips"]["wedged"] == 1
        assert wd.stats["restarts"] == 1
        # the rebuilt engine serves — and a submit racing the poison on
        # the OLD engine fails fast with the retryable error, not a hang
        out = m.engine.submit([5, 6], max_new_tokens=3, timeout_s=60)
        assert out
        with pytest.raises(EngineRestarting):
            old_engine.submit([5, 6], max_new_tokens=3)
    finally:
        release()
        m.unload()


def test_watchdog_trips_on_dead_loop_thread(model_and_params):
    """A scheduler thread that died (fatal device error) trips the
    watchdog without any heartbeat math, and the rebuild recovers."""
    model, params = model_and_params
    m = _loaded_engine_model(model, params, name="wd-dead", watchdog=False)
    wd = EngineWatchdog(
        lambda: m.engine, m.restart_engine, on_ready=m._set_ready,
        config=WatchdogConfig(min_wedge_s=5.0), model_name="wd-dead",
    )
    old_engine = m.engine
    try:
        boom = RuntimeError("injected device failure")

        def exploding_chunk(*a, **k):
            raise boom

        old_engine._chunk = exploding_chunk
        with pytest.raises(RuntimeError, match="injected device failure"):
            old_engine.submit([3, 4, 5], max_new_tokens=6, timeout_s=30)
        assert wd.tick() == "fatal"
        assert m.engine is not old_engine and m.ready
        assert m.engine.submit([5, 6], max_new_tokens=3, timeout_s=60)
        # idle healthy engine: no trip
        assert wd.tick() is None
    finally:
        m.unload()


def test_watchdog_retries_failed_rebuild_until_it_succeeds(
    model_and_params,
):
    """A rebuild that raises leaves the replica not-ready (routed
    around) and is retried on subsequent ticks until one succeeds."""
    model, params = model_and_params
    m = _loaded_engine_model(
        model, params, name="wd-retry", watchdog=False
    )
    attempts = {"n": 0}

    def flaky_rebuild(err):
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise RuntimeError("transient rebuild failure")
        return m.restart_engine(err)

    wd = EngineWatchdog(
        lambda: m.engine, flaky_rebuild, on_ready=m._set_ready,
        config=WatchdogConfig(min_wedge_s=5.0), model_name="wd-retry",
    )
    old_engine = m.engine
    try:
        old_engine._chunk = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("boom")
        )
        with pytest.raises(RuntimeError, match="boom"):
            old_engine.submit([3, 4, 5], max_new_tokens=4, timeout_s=30)
        assert wd.tick() == "fatal"
        assert m.ready is False  # first rebuild attempt failed
        assert m.engine is old_engine
        assert wd.tick() is None  # retry path, not a fresh trip
        assert attempts["n"] == 2
        assert m.ready is True and m.engine is not old_engine
        assert m.engine.submit([5, 6], max_new_tokens=3, timeout_s=60)
    finally:
        m.unload()


def test_watchdog_no_trip_on_idle_or_deliberate_stop(model_and_params):
    model, params = model_and_params
    m = _loaded_engine_model(model, params, name="wd-idle", watchdog=False)
    wd = EngineWatchdog(
        lambda: m.engine, m.restart_engine, on_ready=m._set_ready,
        config=WatchdogConfig(min_wedge_s=0.0, wedge_factor=0.0),
        clock=lambda: time.monotonic() + 1e6,  # everything looks stale
        model_name="wd-idle",
    )
    try:
        assert wd.tick() is None  # idle: busy() is False, stale is fine
        m.engine.stop()
        assert wd.tick() is None  # deliberate stop is not a fault
    finally:
        m.unload()


# ---------------------------------------------- server + header seams


def test_server_maps_sre_errors_and_default_deadline(model_and_params):
    """HTTP seam: an expired x-kft-deadline-ms budget → 503 carrying
    Retry-After (the gateway's non-retryable shed marker); a roomy budget
    → 200; admission shed → 503 + Retry-After ≥ backlog estimate."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.server import ModelServer

    model, params = model_and_params
    m = _loaded_engine_model(model, params, name="lm", watchdog=False)
    server = ModelServer([m])

    async def drive():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post(
                "/v1/models/lm:predict",
                json={"instances": [{"input_ids": [3, 4, 5]}]},
                headers={DEADLINE_HEADER: "30000"},
            )
            assert r.status == 200
            r = await client.post(
                "/v1/models/lm:predict",
                json={"instances": [{"input_ids": [3, 4, 5]}]},
                headers={DEADLINE_HEADER: "0"},
            )
            assert r.status == 503
            assert r.headers.get("Retry-After") == "1"
            assert "deadline" in (await r.text()).lower()
            # admission shed surfaces its backlog estimate
            m.engine.overlap["decode_gap_ms"] = 500.0
            r = await client.post(
                "/v1/models/lm:predict",
                json={"instances": [{"input_ids": [3, 4, 5]}]},
                headers={DEADLINE_HEADER: "300"},
            )
            assert r.status == 503
            assert int(r.headers.get("Retry-After", "0")) >= 1
            m.engine.overlap["decode_gap_ms"] = 0.0
            # SSE path: an expired budget refuses BEFORE committing a 200
            r = await client.post(
                "/v2/models/lm/generate_stream",
                json={"input_ids": [3, 4, 5]},
                headers={DEADLINE_HEADER: "0"},
            )
            assert r.status == 503
            assert r.headers.get("Retry-After") == "1"

    try:
        asyncio.run(drive())
    finally:
        m.unload()


def test_server_default_deadline_applies_when_header_absent(
    model_and_params,
):
    """The KServe request-timeout analog: default_deadline_ms bounds
    header-less requests; an unmeetable default sheds like a client one."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.server import ModelServer

    model, params = model_and_params
    m = _loaded_engine_model(model, params, name="lm", watchdog=False)
    server = ModelServer([m], default_deadline_ms=250.0)
    # make the default provably unmeetable: ~500 ms/chunk × 4 chunks
    m.engine.overlap["decode_gap_ms"] = 500.0

    async def drive():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post(
                "/v1/models/lm:predict",
                json={"instances": [{"input_ids": [3, 4, 5]}]},
            )
            assert r.status == 503
            assert "Retry-After" in r.headers
            # an explicit client budget overrides the server default
            m.engine.overlap["decode_gap_ms"] = 0.0
            r = await client.post(
                "/v1/models/lm:predict",
                json={"instances": [{"input_ids": [3, 4, 5]}]},
                headers={DEADLINE_HEADER: "60000"},
            )
            assert r.status == 200

    try:
        asyncio.run(drive())
    finally:
        m.unload()


def test_chaos_plan_serving_faults_round_trip():
    from kubeflow_tpu.chaos.plan import FaultPlan, SlowDecode, WedgeEngine

    plan = FaultPlan(
        faults=(WedgeEngine(model="lm", hold_s=12.5),
                SlowDecode(model="lm", delay_s=0.25)),
        seed=7,
    )
    again = FaultPlan.from_dict(plan.to_dict())
    assert again == plan
    assert again.faults[0].kind == "WedgeEngine"
    assert again.faults[1].delay_s == 0.25


def test_chaos_runner_fires_serving_faults_without_cluster(
    model_and_params,
):
    """A serving-only FaultPlan drives the engine seams through the
    runner: no cluster, triggers key off engine presence."""
    from kubeflow_tpu.chaos.plan import FaultPlan, SlowDecode
    from kubeflow_tpu.chaos.runner import ChaosRunner

    model, params = model_and_params
    eng = _engine(model, params)
    try:
        runner = ChaosRunner(
            plan=FaultPlan(faults=(SlowDecode(model="lm", delay_s=0.01),)),
            engines={"lm": eng},
        )
        runner.poll()
        assert runner.done
        assert [f.fault.kind for f in runner.fired] == ["SlowDecode"]
        assert "pre_chunk" in eng._fault_hooks
        # the engine still answers correctly under the inflated latency
        assert eng.submit([3, 4], max_new_tokens=3, timeout_s=60)
    finally:
        eng.stop()
