"""gRPC v2 (Open Inference Protocol) servicer: the same DataPlane must
answer the same infer request identically over REST and gRPC (VERDICT r1
item 4; SURVEY.md §2.2 model-server row: reference serves v2 over REST
*and* gRPC)."""

import asyncio

import numpy as np
import pytest

from kubeflow_tpu.serve.grpc_server import (
    GrpcInferenceClient,
    GrpcInferenceServer,
    decode_input_tensor,
    encode_output_tensor,
)
from kubeflow_tpu.serve.model import Model
from kubeflow_tpu.serve.protos import open_inference_pb2 as pb
from kubeflow_tpu.serve.server import ModelServer


class _Doubler(Model):
    def predict(self, inputs, headers=None):
        return {"predictions": [[2 * v for v in row] for row in inputs["instances"]]}


@pytest.fixture()
def server():
    s = ModelServer([_Doubler("dbl")])
    g = GrpcInferenceServer(s.dataplane, port=0)
    port = g.start()
    yield s, g, port
    g.stop()


def test_health_and_metadata(server):
    _, _, port = server
    c = GrpcInferenceClient(f"localhost:{port}")
    assert c.server_ready()
    assert c.model_ready("dbl")
    meta = c._call(
        "ModelMetadata", pb.ModelMetadataRequest(name="dbl"),
        pb.ModelMetadataResponse,
    )
    assert meta.name == "dbl" and meta.platform == "jax-tpu"
    live = c._call("ServerLive", pb.ServerLiveRequest(), pb.ServerLiveResponse)
    assert live.live
    c.close()


def test_model_infer(server):
    _, _, port = server
    c = GrpcInferenceClient(f"localhost:{port}")
    out = c.infer("dbl", {"input_ids": np.array([[1, 2], [3, 4]], np.int32)})
    np.testing.assert_array_equal(out["output_0"], [[2, 4], [6, 8]])
    c.close()


def test_unknown_model_is_not_found(server):
    import grpc

    _, _, port = server
    c = GrpcInferenceClient(f"localhost:{port}")
    with pytest.raises(grpc.RpcError) as ei:
        c.infer("nope", {"x": np.zeros((1, 1), np.int32)})
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND
    c.close()


def test_rest_and_grpc_answer_identically(server):
    """The parity contract: one request, two transports, same numbers."""
    from aiohttp.test_utils import TestClient, TestServer

    s, _, port = server
    body = {
        "inputs": [
            {
                "name": "input_ids",
                "shape": [2, 2],
                "datatype": "INT32",
                "data": [1, 2, 3, 4],
            }
        ]
    }

    async def rest():
        async with TestClient(TestServer(s.build_app())) as client:
            r = await client.post("/v2/models/dbl/infer", json=body)
            assert r.status == 200
            return await r.json()

    rest_out = asyncio.run(rest())
    c = GrpcInferenceClient(f"localhost:{port}")
    grpc_out = c.infer("dbl", {"input_ids": np.array([[1, 2], [3, 4]], np.int32)})
    c.close()

    rest_tensor = rest_out["outputs"][0]
    g = grpc_out["output_0"]
    assert rest_tensor["shape"] == list(g.shape)
    np.testing.assert_array_equal(
        np.asarray(rest_tensor["data"]).reshape(rest_tensor["shape"]), g
    )


def test_raw_contents_roundtrip():
    # raw_input_contents path (the high-throughput binary encoding)
    t = pb.ModelInferRequest.InferInputTensor(
        name="x", datatype="FP32", shape=[2, 3]
    )
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = decode_input_tensor(t, arr.tobytes())
    np.testing.assert_array_equal(out, arr)


def test_fp16_outputs_use_raw():
    tensor, raw = encode_output_tensor("y", np.ones((2, 2), np.float16))
    assert tensor.datatype == "FP16"
    assert raw is not None
    back = np.frombuffer(raw, np.float16).reshape(2, 2)
    np.testing.assert_array_equal(back, np.ones((2, 2), np.float16))


def test_bytes_raw_contents_decode():
    t = pb.ModelInferRequest.InferInputTensor(
        name="text", datatype="BYTES", shape=[2]
    )
    raw = b"".join(
        len(s).to_bytes(4, "little") + s for s in (b"hello", b"wo")
    )
    out = decode_input_tensor(t, raw)
    assert out.tolist() == [b"hello", b"wo"]


def test_shared_batcher_across_transports_no_deadlock():
    """A Batcher coalescing one gRPC and one HTTP request must complete
    both (cross-loop future completion was a confirmed deadlock)."""
    import threading

    from kubeflow_tpu.serve.batcher import BatcherConfig

    s = ModelServer(
        [_Doubler("dbl")],
        http_port=0,
        grpc_port=0,
        batcher=BatcherConfig(max_batch_size=2, max_latency_ms=50.0),
    )

    async def run():
        await s.start_async()
        grpc_result = {}

        def grpc_call():
            c = GrpcInferenceClient(f"localhost:{s.grpc_port}")
            grpc_result["out"] = c.infer(
                "dbl", {"input_ids": np.array([[1, 2]], np.int32)}
            )
            c.close()

        t = threading.Thread(target=grpc_call, daemon=True)
        t.start()
        # HTTP request lands in the same batch window
        rest = await s.dataplane.infer("dbl", {"instances": [[3, 4]]})
        await asyncio.get_running_loop().run_in_executor(None, t.join, 10)
        assert not t.is_alive(), "gRPC request deadlocked in shared batcher"
        np.testing.assert_array_equal(
            grpc_result["out"]["output_0"], [[2, 4]]
        )
        assert rest["predictions"] == [[6, 8]]
        await s.stop_async()

    asyncio.run(run())
