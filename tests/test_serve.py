"""Serving plane tests (SURVEY.md §4: KServe pytest analog — protocol
codecs, Model lifecycle with dummy models, batcher, controller semantics)."""

import asyncio

import numpy as np
import pytest

from kubeflow_tpu.serve import protocol
from kubeflow_tpu.serve.batcher import Batcher, BatcherConfig
from kubeflow_tpu.serve.logger import RequestLogger
from kubeflow_tpu.serve.model import BucketSpec, EchoModel, JAXModel, Model
from kubeflow_tpu.serve.server import ModelServer
from kubeflow_tpu.serve.spec import (
    ComponentSpec,
    InferenceServiceSpec,
    PredictorSpec,
    RuntimeRegistry,
    ServingRuntime,
)
from kubeflow_tpu.serve.controller import InferenceServiceController
from kubeflow_tpu.serve.graph import InferenceGraph, Node, Step
from kubeflow_tpu.serve import storage as storage_mod


# ---------------------------------------------------------------- protocol


def test_v1_codec_roundtrip():
    body = {"instances": [[1, 2], [3, 4]]}
    assert protocol.decode_v1(body) == [[1, 2], [3, 4]]
    out = protocol.encode_v1(np.array([[0.1, 0.9]]))
    assert out == {"predictions": [[pytest.approx(0.1), pytest.approx(0.9)]]}
    with pytest.raises(ValueError):
        protocol.decode_v1({"inputs": []})


def test_v2_codec_roundtrip():
    body = {
        "inputs": [
            {"name": "input_ids", "shape": [2, 3], "datatype": "INT32",
             "data": [1, 2, 3, 4, 5, 6]},
            {"name": "scale", "shape": [1], "datatype": "FP32", "data": [0.5]},
        ]
    }
    tensors = protocol.decode_v2(body)
    assert tensors["input_ids"].shape == (2, 3)
    assert tensors["input_ids"].dtype == np.int32
    assert tensors["scale"].dtype == np.float32

    enc = protocol.encode_v2("m", {"logits": np.ones((1, 2), np.float32)})
    assert enc["outputs"][0]["datatype"] == "FP32"
    assert enc["outputs"][0]["shape"] == [1, 2]

    # bf16 rides the wire as uint16 words
    t = protocol.InferTensor.from_v2(
        {"name": "w", "shape": [2], "datatype": "BF16", "data": [16256, 0]}
    )
    assert t.data.dtype == np.uint16


# ------------------------------------------------------------------ buckets


def test_bucket_spec_rounds_up():
    b = BucketSpec(batch_sizes=(1, 4, 8), seq_lens=(16, 64))
    assert b.bucket_batch(1) == 1
    assert b.bucket_batch(3) == 4
    assert b.bucket_seq(17) == 64
    with pytest.raises(ValueError):
        b.bucket_batch(9)


def test_jax_model_bucketing_prevents_recompiles(devices8):
    """Ragged request shapes must hit a closed set of compiled programs."""
    import jax.numpy as jnp

    def apply_fn(params, ids, mask):
        return (ids * params["w"] * mask).sum(-1)

    m = JAXModel(
        "toy",
        apply_fn,
        lambda: {"w": jnp.int32(2)},
        buckets=BucketSpec(batch_sizes=(1, 4), seq_lens=(8, 16)),
    )
    m.load()
    m.warmup()  # compiles all 4 buckets
    compiles_after_warmup = m.stats["compiles"]
    # Many ragged shapes, all inside existing buckets → zero new compiles.
    for rows in ([[1, 2, 3]], [[1] * 5, [2] * 7], [[9] * 12], [[1], [2], [3]]):
        out = m.predict(m.preprocess({"instances": rows}))
        assert out.shape[0] == len(rows)
    assert m.stats["compiles"] == compiles_after_warmup


def test_jax_model_correct_padding_semantics(devices8):
    def apply_fn(params, ids, mask):
        return (ids * mask).sum(-1)  # padded slots masked out

    m = JAXModel("sum", apply_fn, lambda: {},
                 buckets=BucketSpec(batch_sizes=(4,), seq_lens=(8,)))
    m.load()
    out = m.predict(m.preprocess({"instances": [[1, 2, 3], [10]]}))
    assert out.tolist() == [6, 10]  # batch padding stripped, seq padding masked


# ------------------------------------------------------------------ batcher


def test_batcher_flushes_on_size_and_latency():
    calls = []

    async def handler(flat):
        calls.append(list(flat))
        return [x * 10 for x in flat]

    async def run():
        b = Batcher(handler, BatcherConfig(max_batch_size=4, max_latency_ms=20))
        # size-triggered flush: two submits totalling 4 instances
        r1, r2 = await asyncio.gather(b.submit([1, 2]), b.submit([3, 4]))
        assert r1 == [10, 20] and r2 == [30, 40]
        assert len(calls) == 1 and sorted(calls[0]) == [1, 2, 3, 4]
        # latency-triggered flush: single small submit
        r3 = await b.submit([5])
        assert r3 == [50]
        assert len(calls) == 2
        assert b.stats["batches"] == 2 and b.stats["instances"] == 5

    asyncio.run(run())


def test_batcher_deadline_flush_with_awaiting_handler():
    """Regression: the timer task must not cancel itself mid-handler-await."""

    async def handler(flat):
        await asyncio.sleep(0.01)  # a real TPU forward awaits
        return [x + 1 for x in flat]

    async def run():
        b = Batcher(handler, BatcherConfig(max_batch_size=64, max_latency_ms=5))
        out = await asyncio.wait_for(b.submit([1, 2]), timeout=2.0)
        assert out == [2, 3]

    asyncio.run(run())


def test_batcher_splits_oversize_submits():
    calls = []

    async def handler(flat):
        calls.append(len(flat))
        return [x * 2 for x in flat]

    async def run():
        b = Batcher(handler, BatcherConfig(max_batch_size=4, max_latency_ms=5))
        out = await b.submit(list(range(10)))  # > max_batch_size
        assert out == [x * 2 for x in range(10)]
        assert calls == [4, 4, 2]  # chunked, never above the cap

    asyncio.run(run())


def test_batcher_accumulates_while_handler_runs():
    """Requests arriving during an in-flight forward join the NEXT batch."""
    calls = []
    release = asyncio.Event()

    async def handler(flat):
        calls.append(sorted(flat))
        if len(calls) == 1:
            await release.wait()  # first batch in flight...
        return flat

    async def run():
        b = Batcher(handler, BatcherConfig(max_batch_size=2, max_latency_ms=5))
        t1 = asyncio.create_task(b.submit([1, 2]))  # size-flushes immediately
        await asyncio.sleep(0.01)
        t2 = asyncio.create_task(b.submit([3]))  # queued while #1 in flight
        await asyncio.sleep(0.02)
        release.set()
        assert await asyncio.wait_for(asyncio.gather(t1, t2), 2.0) == [[1, 2], [3]]
        assert calls == [[1, 2], [3]]

    asyncio.run(run())


def test_batcher_propagates_handler_errors():
    async def handler(flat):
        raise RuntimeError("boom")

    async def run():
        b = Batcher(handler, BatcherConfig(max_batch_size=1))
        with pytest.raises(RuntimeError):
            await b.submit([1])

    asyncio.run(run())


def test_batcher_isolation_counts_instances_and_isolations():
    """The isolate-offender path must still count succeeded instances
    (regression: mean_occupancy silently undercounted after any co-batched
    failure) and record the isolation event for /metrics."""

    async def handler(flat):
        if 13 in flat:  # the offender poisons the co-batched run too
            raise ValueError("bad instance")
        return flat

    async def run():
        b = Batcher(handler, BatcherConfig(max_batch_size=8, max_latency_ms=5))
        t_ok = asyncio.create_task(b.submit([1, 2]))
        t_bad = asyncio.create_task(b.submit([13]))
        assert await asyncio.wait_for(t_ok, 2.0) == [1, 2]
        with pytest.raises(ValueError, match="bad instance"):
            await asyncio.wait_for(t_bad, 2.0)
        # the survivor's 2 instances counted; the offender's never succeeded
        assert b.stats["instances"] == 2
        assert b.stats["fail_isolations"] == 1
        assert b.stats["batches"] == 1  # one successful (isolated) call
        assert b.mean_occupancy == 2.0

    asyncio.run(run())


# ------------------------------------------------------------------- server


class _Doubler(Model):
    def predict(self, inputs, headers=None):
        return {"predictions": [[2 * v for v in row] for row in inputs["instances"]]}


def test_model_server_v1_v2_endpoints():
    from aiohttp.test_utils import TestClient, TestServer

    logger = RequestLogger()
    server = ModelServer([_Doubler("dbl")], logger=logger)

    async def run():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.get("/")
            assert (await r.json())["status"] == "alive"
            r = await client.get("/v1/models")
            assert (await r.json())["models"] == ["dbl"]
            r = await client.get("/v1/models/dbl")
            assert (await r.json())["ready"] is True

            r = await client.post(
                "/v1/models/dbl:predict", json={"instances": [[1, 2], [3, 4]]}
            )
            assert (await r.json())["predictions"] == [[2, 4], [6, 8]]

            r = await client.post(
                "/v2/models/dbl/infer",
                json={"inputs": [{"name": "input_ids", "shape": [1, 2],
                                  "datatype": "INT32", "data": [5, 6]}]},
            )
            body = await r.json()
            assert body["outputs"][0]["data"] == [10, 12]

            r = await client.get("/v2/health/ready")
            assert (await r.json())["ready"] is True
            r = await client.get("/metrics")
            text = await r.text()
            assert 'kubeflow_tpu_requests_total{model="dbl"} 2' in text
            assert "latency_p50_ms" in text

            r = await client.post("/v1/models/nope:predict", json={"instances": []})
            assert r.status == 404

    asyncio.run(run())
    # logger captured request+response CloudEvents for both inferences
    kinds = [e["type"] for e in logger.entries]
    assert kinds.count("org.kubeflow.serving.inference.request") == 2
    assert kinds.count("org.kubeflow.serving.inference.response") == 2
    assert all(e["specversion"] == "1.0" for e in logger.entries)


def test_model_server_batching_path():
    server = ModelServer([_Doubler("dbl")],
                         batcher=BatcherConfig(max_batch_size=2, max_latency_ms=10))

    async def run():
        from aiohttp.test_utils import TestClient, TestServer

        async with TestClient(TestServer(server.build_app())) as client:
            r1, r2 = await asyncio.gather(
                client.post("/v1/models/dbl:predict", json={"instances": [[1]]}),
                client.post("/v1/models/dbl:predict", json={"instances": [[2]]}),
            )
            assert (await r1.json())["predictions"] == [[2]]
            assert (await r2.json())["predictions"] == [[4]]

    asyncio.run(run())
    b = server.dataplane._batchers["dbl"]
    assert b.stats["instances"] == 2


def test_batcher_stats_exported_as_gauges():
    """Batcher occupancy rides both /metrics surfaces: the ModelServer's
    own endpoint and the shared prom registry (ObsServer), like the
    engine's pool gauges."""
    server = ModelServer([_Doubler("dbl")],
                         batcher=BatcherConfig(max_batch_size=4, max_latency_ms=5))

    async def run():
        from aiohttp.test_utils import TestClient, TestServer

        async with TestClient(TestServer(server.build_app())) as client:
            await asyncio.gather(
                client.post("/v1/models/dbl:predict", json={"instances": [[1]]}),
                client.post("/v1/models/dbl:predict",
                            json={"instances": [[2], [3]]}),
            )
            r = await client.get("/metrics")
            return await r.text()

    text = asyncio.run(run())
    assert 'kubeflow_tpu_batcher_instances{model="dbl"} 3' in text
    assert 'kubeflow_tpu_batcher_batches{model="dbl"}' in text
    assert 'kubeflow_tpu_batcher_mean_occupancy{model="dbl"}' in text
    assert 'kubeflow_tpu_batcher_fail_isolations{model="dbl"} 0' in text
    # shared registry: the collector refreshes values at scrape time
    from kubeflow_tpu.obs.prom import REGISTRY

    exposed = REGISTRY.expose()
    assert 'kubeflow_tpu_batcher_instances{model="dbl"} 3' in exposed
    assert "# TYPE kubeflow_tpu_batcher_mean_occupancy gauge" in exposed
    # unregister tears the collector down with the batcher
    server.dataplane.unregister("dbl")
    assert ("batcher", "dbl") not in REGISTRY._collectors


def test_http_client_errors_are_400_not_500():
    from aiohttp.test_utils import TestClient, TestServer

    server = ModelServer([_Doubler("dbl")])

    async def run():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post("/v1/models/dbl:predict", json={})
            assert r.status == 400
            r = await client.post("/v2/models/dbl/infer", json={"inputs": []})
            assert r.status == 400

    asyncio.run(run())


def test_dataplane_detects_prediction_count_mismatch():
    from kubeflow_tpu.serve.server import DataPlane

    class Broken(Model):
        def predict(self, inputs, headers=None):
            return {"predictions": [1]}  # wrong length vs instances

    dp = DataPlane()
    m = Broken("b")
    m.ready = True
    dp.register(m, BatcherConfig(max_batch_size=4, max_latency_ms=1))

    async def run():
        with pytest.raises(RuntimeError, match="returned 1 predictions"):
            await dp.infer("b", {"instances": [[1], [2], [3]]})

    asyncio.run(run())


def test_batcher_clamped_to_bucket_max(devices8):
    import jax.numpy as jnp

    def apply_fn(params, ids, mask):
        return (ids * mask).sum(-1)

    m = JAXModel("toy", apply_fn, lambda: {},
                 buckets=BucketSpec(batch_sizes=(1, 4), seq_lens=(8,)))
    server = ModelServer([m], batcher=BatcherConfig(max_batch_size=64,
                                                    max_latency_ms=1))
    b = server.dataplane._batchers["toy"]
    assert b.config.max_batch_size == 4  # clamped to the top batch bucket

    async def run():  # 6 instances > top bucket: chunked, still correct
        from aiohttp.test_utils import TestClient, TestServer

        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post("/v1/models/toy:predict",
                                  json={"instances": [[i] for i in range(6)]})
            assert (await r.json())["predictions"] == list(range(6))

    asyncio.run(run())


def test_bf16_v2_roundtrip():
    import ml_dtypes

    arr = np.asarray([1.5, -2.0], ml_dtypes.bfloat16)
    enc = protocol.InferTensor("w", arr).to_v2()
    assert enc["datatype"] == "BF16"
    dec = protocol.InferTensor.from_v2(enc)
    back = dec.data.view(ml_dtypes.bfloat16)
    assert back.tolist() == [1.5, -2.0]


def test_tokenizer_emits_mask_token():
    from kubeflow_tpu.serve.runtimes import SimpleTokenizer

    tok = SimpleTokenizer(1024)
    ids = tok.encode("the [MASK] ran")
    assert ids[0] == tok.CLS and ids[-1] == tok.SEP
    assert tok.MASK in ids
    assert ids == tok.encode("the [MASK] ran")  # stable across calls


# ------------------------------------------------------------------ storage


def test_storage_file_and_stub_schemes(tmp_path):
    src = tmp_path / "weights"
    src.mkdir()
    (src / "w.bin").write_bytes(b"abc")
    dest = storage_mod.download(f"file://{src}", str(tmp_path / "mnt"))
    import os

    assert os.path.exists(os.path.join(dest, "w.bin"))

    # gs/s3/http are REAL schemes now (serve/cloudstorage.py); only truly
    # unknown schemes fall through to the registry error
    with pytest.raises(RuntimeError, match="no fetcher"):
        storage_mod.download("weird://bucket/model", str(tmp_path / "mnt2"))

    storage_mod.register_fetcher(
        "weird", lambda uri, d: str((src / "w.bin"))
    )
    assert storage_mod.download(
        "weird://bucket/model", str(tmp_path / "m3")
    ).endswith("w.bin")
    storage_mod._FETCHERS.pop("weird")


# --------------------------------------------------------------- controller


def _echo_registry():
    reg = RuntimeRegistry()
    reg.register(ServingRuntime("echo", ("echo",),
                                lambda name, path, **kw: EchoModel(name)))
    return reg


def test_isvc_validate_and_runtime_resolution():
    spec = InferenceServiceSpec("s", PredictorSpec(model_format="echo"))
    spec.validate()
    with pytest.raises(ValueError):
        InferenceServiceSpec(
            "s", PredictorSpec(model_format="echo", min_replicas=2, max_replicas=1)
        ).validate()
    reg = _echo_registry()
    assert reg.resolve(ComponentSpec(model_format="echo")).name == "echo"
    with pytest.raises(ValueError):
        reg.resolve(ComponentSpec(model_format="onnx"))


def test_isvc_from_manifest():
    import yaml
    from pathlib import Path

    path = (
        Path(__file__).resolve().parent.parent
        / "kubeflow_tpu" / "examples" / "manifests" / "bert_isvc.yaml"
    )
    spec = InferenceServiceSpec.from_manifest(yaml.safe_load(path.read_text()))
    assert spec.name == "bert"
    assert spec.predictor.model_format == "huggingface"
    assert spec.predictor.storage_uri == "file:///mnt/models/bert-base-uncased"
    assert spec.predictor.max_replicas == 2
    assert spec.transformer is None

    with pytest.raises(ValueError, match="predictor"):
        InferenceServiceSpec.from_manifest(
            {"kind": "InferenceService", "metadata": {"name": "x"}, "spec": {}}
        )


def test_isvc_controller_deploy_and_canary(tmp_path):
    ctl = InferenceServiceController(_echo_registry(), model_dir=str(tmp_path))
    st = ctl.apply(InferenceServiceSpec("svc", PredictorSpec(model_format="echo")))
    assert st.ready and "PredictorReady" in st.conditions

    # canary rollout at 30%: both models live, traffic split ~30/70
    ctl.apply(
        InferenceServiceSpec(
            "svc", PredictorSpec(model_format="echo", canary_traffic_percent=30)
        )
    )
    st = ctl.get("svc")
    assert st.canary_model is not None and st.default_model is not None
    picks = [ctl.route("svc") for _ in range(400)]
    frac = sum(p is st.canary_model for p in picks) / len(picks)
    assert 0.2 < frac < 0.4

    ctl.promote_canary("svc")
    st = ctl.get("svc")
    assert st.canary_model is None
    assert st.spec.predictor.canary_traffic_percent == 100


def test_isvc_plain_rollout_reloads_model(tmp_path):
    """Regression: re-apply at 100% with a changed spec must swap the model."""
    loads = []
    reg = RuntimeRegistry()

    def factory(name, path, version=0):
        loads.append(version)
        return EchoModel(f"{name}-v{version}")

    reg.register(ServingRuntime("echo", ("echo",), factory))
    ctl = InferenceServiceController(reg, model_dir=str(tmp_path))

    ctl.apply(InferenceServiceSpec(
        "r", PredictorSpec(model_format="echo", extra={"version": 1})))
    m1 = ctl.get("r").default_model
    # identical re-apply: no reload
    ctl.apply(InferenceServiceSpec(
        "r", PredictorSpec(model_format="echo", extra={"version": 1})))
    assert ctl.get("r").default_model is m1 and loads == [1]
    # changed spec at default 100%: model swapped, old unloaded
    ctl.apply(InferenceServiceSpec(
        "r", PredictorSpec(model_format="echo", extra={"version": 2})))
    st = ctl.get("r")
    assert st.default_model is not m1 and not m1.ready
    assert loads == [1, 2] and st.canary_model is None


def test_isvc_scale_to_zero_and_cold_start(tmp_path, monkeypatch):
    ctl = InferenceServiceController(
        _echo_registry(), model_dir=str(tmp_path), idle_scale_to_zero_s=0.0
    )
    ctl.apply(
        InferenceServiceSpec(
            "z", PredictorSpec(model_format="echo", min_replicas=0, max_replicas=2)
        )
    )
    st = ctl.get("z")
    ctl.route("z")  # one request, then idle
    assert ctl.autoscale_tick("z") == 0  # idle > 0s window → scaled to zero
    assert not st.default_model.ready  # HBM released

    m = ctl.route("z")  # next request cold-starts
    assert m.ready and st.replicas.cold_starts == 1

    # concurrency drives scale-up: 5 in-flight @ scale_target=1 → max_replicas
    st.spec.predictor.scale_target = 1
    st.replicas.in_flight = 5
    assert ctl.autoscale_tick("z") == 2


# -------------------------------------------------------------------- graph


def test_inference_graph_nodes():
    from kubeflow_tpu.serve.server import DataPlane

    class Add(Model):
        def __init__(self, name, k):
            super().__init__(name)
            self.k = k
            self.ready = True

        async def __call__(self, payload, headers=None):
            return {"instances": [[v + self.k for v in row]
                                  for row in payload["instances"]]}

    dp = DataPlane()
    dp.register(Add("a1", 1))
    dp.register(Add("a10", 10))

    graph = InferenceGraph(
        {
            "root": Node("Sequence", [Step("s1", model="a1"),
                                      Step("s2", node="fanout")]),
            "fanout": Node("Ensemble", [Step("e1", model="a1"),
                                        Step("e10", model="a10")]),
        },
        dp,
    )

    async def run():
        out = await graph.infer({"instances": [[0]]})
        assert out["e1"]["instances"] == [[2]]
        assert out["e10"]["instances"] == [[11]]

        switch = InferenceGraph(
            {"root": Node("Switch", [
                Step("big", model="a10",
                     condition=lambda p: p["instances"][0][0] > 5),
                Step("small", model="a1"),
            ])},
            dp,
        )
        assert (await switch.infer({"instances": [[9]]}))["instances"] == [[19]]
        assert (await switch.infer({"instances": [[1]]}))["instances"] == [[2]]

        splitter = InferenceGraph(
            {"root": Node("Splitter", [Step("w1", model="a1", weight=1),
                                       Step("w9", model="a10", weight=9)])},
            dp,
        )
        outs = [await splitter.infer({"instances": [[0]]}) for _ in range(200)]
        frac10 = sum(o["instances"][0][0] == 10 for o in outs) / len(outs)
        assert frac10 > 0.75

    asyncio.run(run())


# ------------------------------------------------------- bert runtime (e2e)


def test_bert_runtime_text_to_tokens(devices8):
    from kubeflow_tpu.models.bert import bert_tiny
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.runtimes import BertRuntimeModel

    m = BertRuntimeModel(
        "bert", None, config=bert_tiny(attn_impl="reference"),
        buckets=BucketSpec(batch_sizes=(1, 2), seq_lens=(16,)),
    )
    m.load()
    out = m.postprocess(m.predict(m.preprocess(
        {"instances": ["hello [MASK] world", "the cat sat"]})))
    preds = out["predictions"]
    assert len(preds) == 2 and len(preds[0]) == 16
    assert all(isinstance(t, int) for t in preds[0])


def test_bert_multi_input_mask_changes_answer(devices8):
    """VERDICT r3 weak #3: a v2 client sending attention_mask must get an
    answer computed WITH the mask — masked != unmasked logits."""
    from kubeflow_tpu.models.bert import bert_tiny
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.runtimes import BertRuntimeModel

    m = BertRuntimeModel(
        "bert", None, config=bert_tiny(attn_impl="reference"),
        buckets=BucketSpec(batch_sizes=(1, 2), seq_lens=(8,)),
    )
    m.load()
    ids = np.array([[101, 7, 8, 9, 10, 11, 12, 102]], np.int32)
    full = {"input_ids": ids, "attention_mask": np.ones((1, 8), np.int32)}
    half = {"input_ids": ids,
            "attention_mask": np.array([[1, 1, 1, 1, 0, 0, 0, 0]], np.int32)}
    out_full = m.predict(m.preprocess({"inputs": full}))
    out_half = m.predict(m.preprocess({"inputs": half}))
    assert not np.array_equal(out_full, out_half), (
        "attention_mask was dropped on the named-tensor path"
    )
    # token_type_ids must also reach the model
    tt = {"input_ids": ids, "attention_mask": np.ones((1, 8), np.int32),
          "token_type_ids": np.array([[0, 0, 0, 0, 1, 1, 1, 1]], np.int32)}
    out_tt = m.predict(m.preprocess({"inputs": tt}))
    assert not np.array_equal(out_full, out_tt)


def test_v2_multi_input_rest_and_grpc_roundtrip(devices8):
    """Multi-input v2 requests round-trip over BOTH transports and the two
    transports agree (SURVEY.md §2.2 model-server row)."""
    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.models.bert import bert_tiny
    from kubeflow_tpu.serve.grpc_server import (
        GrpcInferenceClient,
        GrpcInferenceServer,
    )
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.runtimes import BertRuntimeModel

    m = BertRuntimeModel(
        "bert", None, config=bert_tiny(attn_impl="reference"),
        buckets=BucketSpec(batch_sizes=(1, 2), seq_lens=(8,)),
    )
    s = ModelServer([m])
    ids = [[101, 7, 8, 9, 10, 11, 12, 102]]
    mask = [[1, 1, 1, 1, 0, 0, 0, 0]]
    body = {
        "inputs": [
            {"name": "input_ids", "shape": [1, 8], "datatype": "INT32",
             "data": [v for row in ids for v in row]},
            {"name": "attention_mask", "shape": [1, 8], "datatype": "INT32",
             "data": [v for row in mask for v in row]},
        ]
    }

    async def rest(payload):
        async with TestClient(TestServer(s.build_app())) as client:
            r = await client.post("/v2/models/bert/infer", json=payload)
            assert r.status == 200, await r.text()
            return await r.json()

    masked = asyncio.run(rest(body))
    unmasked = asyncio.run(rest({"inputs": body["inputs"][:1]}))
    assert masked["outputs"][0]["data"] != unmasked["outputs"][0]["data"], (
        "REST v2 dropped attention_mask"
    )

    g = GrpcInferenceServer(s.dataplane, port=0)
    port = g.start()
    try:
        c = GrpcInferenceClient(f"localhost:{port}")
        out = c.infer("bert", {
            "input_ids": np.asarray(ids, np.int32),
            "attention_mask": np.asarray(mask, np.int32),
        })
        c.close()
    finally:
        g.stop()
    rest_tensor = masked["outputs"][0]
    np.testing.assert_array_equal(
        np.asarray(rest_tensor["data"]).reshape(rest_tensor["shape"]),
        out["output_0"],
    )


def test_ragged_named_row_is_rejected_not_batch_poison(devices8):
    """A mask shorter than input_ids must 400 with a clear message (and not
    crash co-batched requests inside the shared batcher)."""
    from kubeflow_tpu.models.bert import bert_tiny
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.runtimes import BertRuntimeModel

    m = BertRuntimeModel(
        "bert", None, config=bert_tiny(attn_impl="reference"),
        buckets=BucketSpec(batch_sizes=(1, 2), seq_lens=(8,)),
    )
    m.load()
    with pytest.raises(ValueError, match="attention_mask length"):
        m.preprocess({"instances": [
            {"input_ids": [101, 7, 8, 102], "attention_mask": [1, 1]}
        ]})


def test_batcher_isolates_failing_caller():
    """One malformed request in a coalesced batch fails ONLY its caller."""
    async def run():
        calls = []

        async def handler(flat):
            calls.append(list(flat))
            if any(x == "bad" for x in flat):
                raise ValueError("malformed instance")
            return [2 * x for x in flat]

        b = Batcher(handler, BatcherConfig(max_batch_size=4, max_latency_ms=20))
        good, bad = asyncio.ensure_future(b.submit([1, 2])), asyncio.ensure_future(
            b.submit(["bad"])
        )
        res = await asyncio.gather(good, bad, return_exceptions=True)
        assert res[0] == [2, 4]
        assert isinstance(res[1], ValueError)

    asyncio.run(run())


# ------------------------------------------------ sklearn runtime (non-NLP)


def test_sklearn_linear_runtime_jitted_matches_sklearn(tmp_path, devices8):
    """VERDICT r3 missing #5: the registry generalizes beyond BERT — a
    pickled LogisticRegression serves through the jitted device path and
    agrees with sklearn's own predict."""
    import joblib
    from sklearn.linear_model import LinearRegression, LogisticRegression

    from kubeflow_tpu.serve.sklearn_runtime import SklearnRuntimeModel

    rng = np.random.RandomState(0)
    X = rng.randn(200, 5)
    y = (X @ [1.0, -2.0, 0.5, 0.0, 1.5] > 0).astype(int)
    clf = LogisticRegression().fit(X, y)
    joblib.dump(clf, tmp_path / "model.joblib")

    m = SklearnRuntimeModel("sk", str(tmp_path))
    m.load()
    assert m._jitted is not None, "linear model should take the device path"
    Xq = rng.randn(16, 5)
    out = m.predict(m.preprocess({"instances": Xq.tolist()}))
    np.testing.assert_array_equal(out, clf.predict(Xq))

    # regression flavor
    reg = LinearRegression().fit(X, X @ [1, 2, 3, 4, 5.0])
    joblib.dump(reg, tmp_path / "reg" / "model.joblib") if (
        (tmp_path / "reg").mkdir() or True
    ) else None
    m2 = SklearnRuntimeModel("skr", str(tmp_path / "reg"))
    m2.load()
    out2 = m2.predict(m2.preprocess({"instances": Xq.tolist()}))
    np.testing.assert_allclose(out2, reg.predict(Xq), rtol=1e-4)


def test_sklearn_nonlinear_falls_back_to_host(tmp_path, devices8):
    import joblib
    from sklearn.tree import DecisionTreeClassifier

    from kubeflow_tpu.serve.sklearn_runtime import SklearnRuntimeModel

    rng = np.random.RandomState(1)
    X = rng.randn(100, 4)
    y = (X[:, 0] * X[:, 1] > 0).astype(int)
    tree = DecisionTreeClassifier(max_depth=4).fit(X, y)
    joblib.dump(tree, tmp_path / "model.pkl")
    m = SklearnRuntimeModel("tree", str(tmp_path))
    m.load()
    assert m._jitted is None
    Xq = rng.randn(8, 4)
    np.testing.assert_array_equal(
        m.predict(m.preprocess({"instances": Xq.tolist()})), tree.predict(Xq)
    )


def test_sklearn_runtime_through_registry_and_server(tmp_path, devices8):
    """End-to-end: ISVC resolves format 'sklearn' from the default registry
    and the model answers over the v1 REST protocol."""
    import joblib
    from sklearn.linear_model import LogisticRegression

    from kubeflow_tpu.serve.controller import InferenceServiceController
    from kubeflow_tpu.serve.runtimes import default_registry
    from kubeflow_tpu.serve.spec import InferenceServiceSpec, PredictorSpec

    rng = np.random.RandomState(2)
    X = rng.randn(100, 3)
    y = (X.sum(1) > 0).astype(int)
    src = tmp_path / "m"
    src.mkdir()
    joblib.dump(LogisticRegression().fit(X, y), src / "model.joblib")

    ctl = InferenceServiceController(
        default_registry(), model_dir=str(tmp_path / "dl")
    )
    st = ctl.apply(
        InferenceServiceSpec(
            name="sk",
            predictor=PredictorSpec(
                model_format="sklearn", storage_uri=f"file://{src}"
            ),
        )
    )
    assert st.ready
    model = ctl.route("sk")
    s = ModelServer([model])

    async def run():
        from aiohttp.test_utils import TestClient, TestServer

        async with TestClient(TestServer(s.build_app())) as client:
            r = await client.post(
                "/v1/models/sk:predict",
                json={"instances": [[1.0, 1.0, 1.0], [-2.0, -1.0, -1.0]]},
            )
            assert r.status == 200
            return (await r.json())["predictions"]

    preds = asyncio.run(run())
    assert preds == [1, 0]


def test_sklearn_fail_closed_on_garbage(tmp_path, devices8):
    from kubeflow_tpu.serve.sklearn_runtime import SklearnRuntimeModel

    (tmp_path / "model.pkl").write_bytes(b"not a pickle")
    m = SklearnRuntimeModel("bad", str(tmp_path))
    with pytest.raises(Exception):
        m.load()
    assert not m.ready


# ------------------------------------------- storage machinery (retry etc.)


def test_storage_retries_transient_fetcher_failures(tmp_path):
    calls = {"n": 0}

    def flaky(uri, staging):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient network error")
        p = f"{staging}/weights.bin"
        open(p, "wb").write(b"payload")
        return p

    storage_mod.register_fetcher("flaky", flaky)
    try:
        out = storage_mod.download(
            "flaky://bucket/weights.bin", str(tmp_path), backoff_s=0.001
        )
    finally:
        storage_mod._FETCHERS.pop("flaky", None)
    assert calls["n"] == 3
    assert open(out, "rb").read() == b"payload"
    assert storage_mod.verify(out)


def test_storage_partial_download_never_visible(tmp_path):
    def dies_halfway(uri, staging):
        open(f"{staging}/model.bin", "wb").write(b"half")
        raise RuntimeError("connection reset")

    storage_mod.register_fetcher("dead", dies_halfway)
    try:
        with pytest.raises(RuntimeError, match="after 2 attempts"):
            storage_mod.download(
                "dead://x/model.bin", str(tmp_path), retries=2, backoff_s=0.001
            )
    finally:
        storage_mod._FETCHERS.pop("dead", None)
    # nothing but (cleaned) staging leftovers — no half-written model
    visible = [
        p.name for p in tmp_path.iterdir() if not p.name.startswith(".staging")
    ]
    assert visible == []


def test_storage_checksum_pin_and_corruption_detection(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    f = src / "model.bin"
    f.write_bytes(b"golden weights")
    import hashlib

    good = hashlib.sha256(b"golden weights").hexdigest()
    dl = tmp_path / "dl"
    out = storage_mod.download(
        f"file://{f}", str(dl), expected_sha256=good
    )
    assert storage_mod.verify(out)
    with pytest.raises(RuntimeError, match="checksum mismatch"):
        storage_mod.download(
            f"file://{f}", str(tmp_path / "dl2"),
            expected_sha256="0" * 64, retries=1, backoff_s=0.001,
        )
    # bit-rot detection: corrupt the downloaded copy → verify goes false,
    # and a re-download repairs it
    open(out, "wb").write(b"rotted")
    assert not storage_mod.verify(out)
    out2 = storage_mod.download(f"file://{f}", str(dl))
    assert open(out2, "rb").read() == b"golden weights"


def test_storage_verified_cache_skips_refetch(tmp_path):
    calls = {"n": 0}

    def counting(uri, staging):
        calls["n"] += 1
        p = f"{staging}/m.bin"
        open(p, "wb").write(b"v1")
        return p

    storage_mod.register_fetcher("count", counting)
    try:
        a = storage_mod.download("count://x/m.bin", str(tmp_path))
        b = storage_mod.download("count://x/m.bin", str(tmp_path))
    finally:
        storage_mod._FETCHERS.pop("count", None)
    assert a == b and calls["n"] == 1  # second call was a verified cache hit


def test_sklearn_ovo_svc_stays_on_host_and_correct(tmp_path, devices8):
    """SVC(kernel='linear') exposes pairwise coef_ (OVO); it must NOT take
    the argmax device path — predictions must equal sklearn's voting."""
    import joblib
    from sklearn.svm import SVC

    from kubeflow_tpu.serve.sklearn_runtime import SklearnRuntimeModel

    rng = np.random.RandomState(3)
    X = rng.randn(120, 4)
    y = rng.randint(0, 3, 120)  # 3 classes: n(n-1)/2 == n edge case
    svc = SVC(kernel="linear").fit(X, y)
    joblib.dump(svc, tmp_path / "model.joblib")
    m = SklearnRuntimeModel("svc", str(tmp_path))
    m.load()
    assert m._jitted is None, "OVO estimator must not take the argmax path"
    Xq = rng.randn(16, 4)
    np.testing.assert_array_equal(
        m.predict(m.preprocess({"instances": Xq.tolist()})), svc.predict(Xq)
    )


# ---------------------------------------- transformer/explainer components


def test_transformer_component_brackets_predictor():
    """KServe transformer semantics: its pre/postprocess bracket the
    predictor's full lifecycle — in-process on TPU (serve/composite.py)."""
    from kubeflow_tpu.serve.composite import ComposedService

    class Upper(Model):  # the "tokenizer service" analog
        def preprocess(self, payload, headers=None):
            return {"instances": [s.upper() for s in payload["instances"]]}

        def postprocess(self, outputs, headers=None):
            return {"predictions": [f"<{p}>" for p in outputs["predictions"]]}

    class Echo(Model):
        def predict(self, inputs, headers=None):
            return {"predictions": list(inputs["instances"])}

    svc = ComposedService("svc", Echo("p"), transformer=Upper("t"))
    out = asyncio.run(svc({"instances": ["a", "b"]}))
    assert out == {"predictions": ["<A>", "<B>"]}


def test_explainer_component_and_v1_explain_endpoint(tmp_path, devices8):
    """:explain routes to the explainer; sklearn linear attributions are
    exact: contributions + intercept reconstruct the decision function."""
    import joblib
    from sklearn.linear_model import LogisticRegression

    from kubeflow_tpu.serve.controller import InferenceServiceController
    from kubeflow_tpu.serve.runtimes import default_registry
    from kubeflow_tpu.serve.spec import ComponentSpec

    rng = np.random.RandomState(4)
    X = rng.randn(80, 3)
    y = (X @ [2.0, -1.0, 0.5] > 0).astype(int)
    clf = LogisticRegression().fit(X, y)
    src = tmp_path / "m"
    src.mkdir()
    joblib.dump(clf, src / "model.joblib")

    ctl = InferenceServiceController(
        default_registry(), model_dir=str(tmp_path / "dl")
    )
    st = ctl.apply(
        InferenceServiceSpec(
            name="sk",
            predictor=PredictorSpec(
                model_format="sklearn", storage_uri=f"file://{src}"
            ),
            explainer=ComponentSpec(
                model_format="sklearn", storage_uri=f"file://{src}"
            ),
        )
    )
    assert st.ready
    server = ModelServer([ctl.route("sk")])

    async def run():
        from aiohttp.test_utils import TestClient, TestServer

        async with TestClient(TestServer(server.build_app())) as client:
            body = {"instances": [[1.0, 2.0, 3.0]]}
            r = await client.post("/v1/models/sk:explain", json=body)
            assert r.status == 200, await r.text()
            exp = (await r.json())["explanations"][0]
            # exact linearity: sum(contributions) + intercept == decision fn
            total = sum(exp["contributions"]) + exp["intercept"][0]
            want = float(clf.decision_function([[1.0, 2.0, 3.0]])[0])
            assert abs(total - want) < 1e-6
            # predict still works through the composed service
            r = await client.post("/v1/models/sk:predict", json=body)
            assert r.status == 200

    asyncio.run(run())


def test_explain_without_explainer_is_501():
    from aiohttp.test_utils import TestClient, TestServer

    server = ModelServer([_Doubler("dbl")])

    async def run():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post(
                "/v1/models/dbl:explain", json={"instances": [[1]]}
            )
            assert r.status == 501

    asyncio.run(run())


def test_graph_spec_manifest_and_conditions():
    """GraphSpec accepts the reference InferenceGraph manifest shape 1:1
    and rejects broken graphs at admission (SURVEY.md §2.2 graph row)."""
    from kubeflow_tpu.serve.graph import GraphSpec, parse_condition

    doc = {
        "apiVersion": "serving.kserve.io/v1alpha1",
        "kind": "InferenceGraph",
        "metadata": {"name": "router"},
        "spec": {
            "nodes": {
                "root": {
                    "routerType": "Switch",
                    "steps": [
                        {"serviceName": "big",
                         "condition": "instances.0.0 > 5"},
                        {"nodeName": "fanout", "name": "rest"},
                    ],
                },
                "fanout": {
                    "routerType": "Ensemble",
                    "steps": [{"serviceName": "a"}, {"serviceName": "b"}],
                },
            }
        },
    }
    g = GraphSpec.from_manifest(doc)
    assert g.name == "router"
    assert g.nodes["root"].kind == "Switch"
    assert g.services() == {"big", "a", "b"}

    # condition language
    assert parse_condition("instances.0.0 > 5")({"instances": [[9]]})
    assert not parse_condition("instances.0.0 > 5")({"instances": [[1]]})
    assert parse_condition('label == "cat"')({"label": "cat"})
    assert parse_condition("tags contains 3")({"tags": [1, 3]})
    assert parse_condition("meta.flag")({"meta": {"flag": True}})
    assert not parse_condition("meta.flag")({})
    assert not parse_condition("a.b > 1")({"a": {"b": "str"}})  # no 500s
    # leftmost-operator split: op characters inside literals don't confuse
    assert parse_condition('label != "a==b"')({"label": "x"})
    assert not parse_condition('label != "a==b"')({"label": "a==b"})
    assert parse_condition('tag contains "a<b"')({"tag": ["a<b"]})
    # mistyped operators are admission errors, not dead branches
    with pytest.raises(ValueError, match="no operator"):
        parse_condition("instances.0.0 = 5")
    with pytest.raises(ValueError, match="no operator"):
        parse_condition("tags contains3")

    # admission failures
    bad = {**doc, "spec": {"nodes": {"other": doc["spec"]["nodes"]["fanout"]}}}
    with pytest.raises(ValueError, match="root"):
        GraphSpec.from_manifest(bad)
    cyc = {
        **doc,
        "spec": {"nodes": {
            "root": {"routerType": "Sequence",
                     "steps": [{"nodeName": "root"}]},
        }},
    }
    with pytest.raises(ValueError, match="cycle"):
        GraphSpec.from_manifest(cyc)
    both = {
        **doc,
        "spec": {"nodes": {"root": {"routerType": "Sequence", "steps": [
            {"serviceName": "x", "nodeName": "root"}]}}},
    }
    with pytest.raises(ValueError, match="exactly one"):
        GraphSpec.from_manifest(both)
    dupe = {
        **doc,
        "spec": {"nodes": {"root": {"routerType": "Ensemble", "steps": [
            {"serviceName": "a", "name": "out"},
            {"serviceName": "b", "name": "out"},
        ]}}},
    }
    with pytest.raises(ValueError, match="duplicate step names"):
        GraphSpec.from_manifest(dupe)


def test_graph_served_over_rest():
    """The VERDICT 'done' bar: a Switch + Ensemble graph manifest served
    over REST — deploy path, not just the routing library."""
    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.platform import manifests
    from kubeflow_tpu.serve.graph import GraphSpec

    class Add(Model):
        def __init__(self, name, k):
            super().__init__(name)
            self.k = k
            self.ready = True

        def load(self):
            self.ready = True
            return True

        async def __call__(self, payload, headers=None):
            return {"instances": [[v + self.k for v in row]
                                  for row in payload["instances"]]}

    doc = {
        "kind": "InferenceGraph",
        "metadata": {"name": "router"},
        "spec": {"nodes": {
            "root": {"routerType": "Switch", "steps": [
                {"serviceName": "a100", "condition": "instances.0.0 >= 50"},
                {"nodeName": "fanout", "name": "small"},
            ]},
            "fanout": {"routerType": "Ensemble", "steps": [
                {"serviceName": "a1", "name": "one"},
                {"serviceName": "a10", "name": "ten"},
            ]},
        }},
    }
    spec = manifests.parse(doc)          # kind-dispatch, like kft serve
    assert isinstance(spec, GraphSpec)

    server = ModelServer([Add("a1", 1), Add("a10", 10), Add("a100", 100)])
    server.register_graph(spec)

    async def run():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.get("/v1/graphs")
            assert (await r.json()) == {"graphs": ["router"]}
            # big input → Switch first branch
            r = await client.post("/v1/graphs/router:infer",
                                  json={"instances": [[60]]})
            assert (await r.json())["instances"] == [[160]]
            # small input → Ensemble fan-out, merged by step name
            r = await client.post("/v1/graphs/router:infer",
                                  json={"instances": [[2]]})
            out = await r.json()
            assert out["one"]["instances"] == [[3]]
            assert out["ten"]["instances"] == [[12]]
            r = await client.post("/v1/graphs/nope:infer", json={})
            assert r.status == 404

    asyncio.run(run())

    # a graph referencing an unregistered model is rejected at register
    lone = ModelServer([Add("a1", 1)])
    with pytest.raises(ValueError, match="not on"):
        lone.register_graph(spec)


def test_compilation_cache_speeds_second_cold_start(tmp_path):
    """The cold-start lever (BASELINE config 5): two fresh processes load
    + warm the same runtime; the second must hit the persistent
    compilation cache (entries on disk, faster warm)."""
    import os
    import subprocess
    import sys

    cache_dir = str(tmp_path / "xla-cache")
    prog = (
        "import time, jax; jax.config.update('jax_platforms','cpu');\n"
        "from kubeflow_tpu.models.bert import bert_tiny\n"
        "from kubeflow_tpu.serve.model import BucketSpec\n"
        "from kubeflow_tpu.serve.runtimes import BertRuntimeModel\n"
        "from kubeflow_tpu.serve.server import ModelServer\n"
        "t0 = time.perf_counter()\n"
        "m = BertRuntimeModel('b', None,"
        " config=bert_tiny(attn_impl='reference'),"
        " buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)))\n"
        "s = ModelServer([m]); m.warmup()\n"
        "print('COLD', time.perf_counter() - t0)\n"
    )
    env = dict(
        os.environ, KFT_COMPILATION_CACHE_DIR=cache_dir, JAX_PLATFORMS="cpu"
    )
    # ambient settings on a developer machine must not defeat the test's
    # own cache dir (compcache keeps a pre-set JAX dir verbatim)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.pop("KFT_NO_COMPILATION_CACHE", None)

    def run():
        r = subprocess.run(
            [sys.executable, "-c", prog], capture_output=True, text=True,
            env=env, timeout=300,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        return float(r.stdout.split("COLD")[1].strip())

    t_first = run()
    entries = set(os.listdir(cache_dir))
    assert entries, "no persistent cache entries written"
    t_second = run()
    after = set(os.listdir(cache_dir))
    # the second run must REUSE the first run's entries. Exact equality is
    # flaky under a loaded host (a straggling async write from run 1 can
    # land during run 2's listing), so: nothing disappears, and at most a
    # straggler or two appears — a cold second run would re-add many.
    assert entries <= after, (entries - after)
    assert len(after) - len(entries) <= 2, (len(entries), len(after))
    # generous bound: CPU compiles are quick and the host may be loaded;
    # a cache MISS path would not be faster at all
    assert t_second < t_first * 2.0, (t_first, t_second)


def test_compilation_cache_opt_out(tmp_path, monkeypatch):
    from kubeflow_tpu.core.compcache import enable_compilation_cache

    monkeypatch.setenv("KFT_NO_COMPILATION_CACHE", "1")
    assert enable_compilation_cache(str(tmp_path / "x")) is None
    assert not (tmp_path / "x").exists()
