"""Continuous-batching LM engine (serve/engine.py): scheduling must never
change numerics. Every completion must equal the whole-batch
``make_generate_fn`` path's answer for the same prompt (greedy), while rows
are admitted into a RUNNING batch and recycled as requests finish."""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.transformer import TransformerConfig, TransformerLM
from kubeflow_tpu.serve.engine import LMEngine
from kubeflow_tpu.serve.generate import make_generate_fn

CFG = TransformerConfig(
    vocab_size=89,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    causal=True,
    max_seq_len=256,
    attn_impl="reference",
    dtype=jnp.float32,
)
EOS = 1


@pytest.fixture(scope="module")
def model_and_params():
    model = TransformerLM(CFG)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return model, params


def _reference_completion(model, params, ids, max_new):
    """The pinned-correct whole-batch path, batch 1, greedy."""
    gen = jax.jit(
        make_generate_fn(model, CFG, max_new_tokens=max_new, eos_id=EOS)
    )
    P = 32 if len(ids) <= 32 else 128
    prompt = np.zeros((1, P), np.int32)
    prompt[0, : len(ids)] = ids
    toks, n_valid = gen(
        params,
        prompt,
        np.asarray([len(ids)], np.int32),
        jax.random.PRNGKey(7),
        np.zeros((1,), np.float32),
    )
    return [int(t) for t in np.asarray(toks)[0, : int(n_valid[0])]]


def _prompts(rng, n, lo=3, hi=20):
    return [
        [int(x) for x in rng.integers(2, CFG.vocab_size, size=rng.integers(lo, hi))]
        for _ in range(n)
    ]


def test_engine_matches_batch_generate_exactly(model_and_params):
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=4, max_seq=64, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        rng = np.random.default_rng(0)
        for ids in _prompts(rng, 6):
            got = eng.submit(ids, max_new_tokens=12)
            want = _reference_completion(model, params, ids, 12)
            assert got == want, (ids, got, want)
    finally:
        eng.stop()


def test_concurrent_staggered_requests_share_the_batch(model_and_params):
    """Requests arriving WHILE others decode join the running batch (the
    defining continuous-batching property), and every answer still equals
    the reference path."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=3, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    rng = np.random.default_rng(1)
    prompts = _prompts(rng, 7)
    results: dict[int, list[int]] = {}
    errors: list[Exception] = []

    def worker(i):
        try:
            time.sleep(0.03 * i)  # staggered arrivals
            results[i] = eng.submit(prompts[i], max_new_tokens=16)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(7)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
    finally:
        eng.stop()
    assert not errors, errors
    assert len(results) == 7
    for i, ids in enumerate(prompts):
        want = _reference_completion(model, params, ids, 16)
        assert results[i] == want, (i, results[i], want)
    # 7 requests through 3 rows: recycling happened, and the batch really
    # was shared (more than one row concurrently occupied at some point)
    assert eng.stats["admitted"] == 7
    assert eng.stats["completed"] == 7
    assert eng.stats["max_concurrent"] >= 2
    assert eng.stats["max_concurrent"] <= 3


@pytest.mark.slow
def test_eos_frees_row_early(model_and_params):
    """A prompt whose continuation hits EOS quickly must finish without
    waiting for long-running neighbours."""
    model, params = model_and_params
    # find a prompt with a short greedy completion (EOS within 6 tokens)
    rng = np.random.default_rng(2)
    short = long_ = None
    for ids in _prompts(rng, 200, lo=3, hi=12):
        n = len(_reference_completion(model, params, ids, 24))
        if n < 6 and short is None:
            short = ids
        elif n >= 10 and long_ is None:
            long_ = ids
        if short is not None and long_ is not None:
            break
    if short is None or long_ is None:
        pytest.skip("random init produced no short/long completion pair")
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        t_long: dict = {}

        def run_long():
            t0 = time.monotonic()
            t_long["out"] = eng.submit(long_, max_new_tokens=24)
            t_long["dt"] = time.monotonic() - t0

        th = threading.Thread(target=run_long)
        th.start()
        time.sleep(0.05)
        t0 = time.monotonic()
        out_short = eng.submit(short, max_new_tokens=24)
        dt_short = time.monotonic() - t0
        th.join(120)
    finally:
        eng.stop()
    assert out_short == _reference_completion(model, params, short, 24)
    assert t_long["out"] == _reference_completion(model, params, long_, 24)
    # the short request must not be held hostage by the long one
    assert dt_short <= t_long["dt"] + 0.5


def test_budget_gating_never_overruns_cache(model_and_params):
    """max_new smaller than chunk_steps: the device must stop advancing the
    row mid-chunk (budget gate), and the answer is exactly the first
    max_new reference tokens."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=64, chunk_steps=8,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        ids = [5, 9, 33, 60]
        got = eng.submit(ids, max_new_tokens=3)
        want = _reference_completion(model, params, ids, 24)[:3]
        # reference may EOS before 3; engine must agree either way
        assert got == _reference_completion(model, params, ids, 3) or got == want
    finally:
        eng.stop()


def test_bad_request_fails_fast_without_killing_engine(model_and_params):
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=40, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit([])
        with pytest.raises(ValueError, match="exceeds engine max_seq"):
            eng.submit([3, 4, 5], max_new_tokens=32)  # 32+32 > 40
        # engine still serves afterwards
        out = eng.submit([3, 4, 5], max_new_tokens=4)
        assert out == _reference_completion(model, params, [3, 4, 5], 4)
    finally:
        eng.stop()


def test_rest_concurrent_requests_share_engine(model_and_params):
    """Through the REAL ModelServer: N concurrent HTTP requests must share
    the engine's decode batch (max_concurrent > 1) and each get exactly the
    reference answer."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer

    model, params = model_and_params
    m = LMEngineModel(
        "lm", None, config=CFG, max_batch=4, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=12, eos_id=EOS,
    )
    m.load()
    m._params = jax.device_put(params)  # pin the fixture weights
    m.engine.stop()
    from kubeflow_tpu.serve.engine import LMEngine as _E

    m.engine = _E(
        m._model, CFG, params, max_batch=4, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    server = ModelServer([m])
    rng = np.random.default_rng(3)
    prompts = _prompts(rng, 5)

    async def fire():
        async with TestClient(TestServer(server.build_app())) as client:
            async def one(ids):
                r = await client.post(
                    "/v1/models/lm:predict",
                    json={"instances": [{"input_ids": ids}]},
                )
                assert r.status == 200
                return (await r.json())["predictions"][0]["token_ids"]

            return await asyncio.gather(*[one(p) for p in prompts])

    results = asyncio.run(fire())
    try:
        for ids, got in zip(prompts, results):
            assert got == _reference_completion(model, params, ids, 12)
        assert m.engine.stats["max_concurrent"] >= 2
    finally:
        m.unload()


def test_chunk_failure_fails_requests_not_hangs(model_and_params):
    """If the device chunk program dies, in-flight submits must get the
    REAL error promptly and later submits must fail fast — never a silent
    dead scheduler thread + timeout."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        boom = RuntimeError("injected device failure")

        def exploding_chunk(*a, **k):
            raise boom

        eng._chunk = exploding_chunk
        with pytest.raises(RuntimeError, match="injected device failure"):
            eng.submit([3, 4, 5], max_new_tokens=8, timeout_s=30)
        with pytest.raises(RuntimeError, match="engine is dead"):
            eng.submit([3, 4, 5], max_new_tokens=8, timeout_s=30)
    finally:
        eng.stop()


def test_generate_stream_sse(model_and_params):
    """generate_stream must deliver tokens INCREMENTALLY (multiple SSE
    frames, chunk-sized), and their concatenation equals the reference
    completion; /generate returns the same thing at once."""
    import asyncio
    import json as jsonlib

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer

    model, params = model_and_params
    m = LMEngineModel(
        "lm", None, config=CFG, max_batch=2, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=12, eos_id=EOS,
    )
    m.load()
    m._params = jax.device_put(params)
    m.engine.stop()
    m.engine = LMEngine(
        m._model, CFG, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    server = ModelServer([m])
    ids = [7, 11, 13, 17, 19]
    want = _reference_completion(model, params, ids, 12)

    async def drive():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post(
                "/v2/models/lm/generate_stream", json={"input_ids": ids}
            )
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/event-stream")
            frames = []
            async for line in r.content:
                line = line.decode().strip()
                if line.startswith("data: "):
                    frames.append(jsonlib.loads(line[len("data: "):]))
            r2 = await client.post(
                "/v2/models/lm/generate", json={"input_ids": ids}
            )
            assert r2.status == 200
            return frames, await r2.json()

    try:
        frames, whole = asyncio.run(drive())
    finally:
        m.unload()
    token_frames = [f for f in frames if "token_ids" in f]
    got = [t for f in token_frames for t in f["token_ids"]]
    assert got == want
    assert frames[-1] == {"done": True, "n_tokens": len(want)}
    if len(want) > 3:  # chunk_steps=2 → streaming really was incremental
        assert len(token_frames) >= 2
    assert whole["token_ids"] == want


def test_generate_stream_501_for_non_engine_models(model_and_params):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer
    from kubeflow_tpu.serve.generate import LMRuntimeModel

    m = LMRuntimeModel(
        "plain", None, config=CFG, max_new_tokens=4,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)), eos_id=EOS,
    )
    m.load()
    server = ModelServer([m])

    async def drive():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post(
                "/v2/models/plain/generate_stream", json={"input_ids": [3]}
            )
            return r.status

    assert asyncio.run(drive()) == 501


def test_stop_fails_inflight_requests_promptly(model_and_params):
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=1, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    errors: list[Exception] = []

    def worker():
        try:
            eng.submit([3, 4, 5] * 4, max_new_tokens=24, timeout_s=60)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    th = threading.Thread(target=worker)
    th.start()
    time.sleep(0.3)  # let it admit / start decoding
    t0 = time.monotonic()
    eng.stop()
    th.join(20)
    assert not th.is_alive()
    # the submit either completed before stop() or failed PROMPTLY with
    # the truth — never a 60s timeout hang
    assert time.monotonic() - t0 < 15
    if errors:
        assert "stopped" in str(errors[0])


def test_sse_disconnect_frees_the_row(model_and_params):
    """Client walks away mid-stream: the engine row must be RELEASED (next
    request on a max_batch=1 engine proceeds), not decode to completion
    for nobody."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer

    model, params = model_and_params
    m = LMEngineModel(
        "lm", None, config=CFG, max_batch=1, chunk_steps=1,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=64, eos_id=EOS,
    )
    m.load()
    m._params = jax.device_put(params)
    m.engine.stop()
    m.engine = LMEngine(
        m._model, CFG, params, max_batch=1, max_seq=128, chunk_steps=1,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    server = ModelServer([m])

    async def drive():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post(
                "/v2/models/lm/generate_stream",
                json={"input_ids": [3, 5, 7]},
            )
            assert r.status == 200
            # read ONE frame, then abandon the stream
            async for line in r.content:
                if line.decode().startswith("data: "):
                    break
            r.close()
            # the single row must come free for the next request
            r2 = await client.post(
                "/v2/models/lm/generate", json={"input_ids": [9, 2, 4]}
            )
            assert r2.status == 200
            return await r2.json()

    try:
        out = asyncio.run(drive())
        assert isinstance(out["token_ids"], list)
    finally:
        m.unload()


def test_reload_cycle_and_engine_metrics(model_and_params):
    """ModelMesh-style load→unload→load must yield a working engine (fresh
    executor + scheduler), and /metrics exports the engine gauges."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer

    model, params = model_and_params
    m = LMEngineModel(
        "lm", None, config=CFG, max_batch=2, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=6, eos_id=EOS,
    )
    m.load()
    m.unload()
    assert not m.ready and m.engine is None
    m.load()  # the reload a mesh eviction + readmission performs
    try:
        out = m.engine.submit([4, 8, 15], max_new_tokens=4)
        assert isinstance(out, list)
        server = ModelServer([m])

        async def scrape():
            async with TestClient(TestServer(server.build_app())) as client:
                r = await client.post(
                    "/v1/models/lm:predict",
                    json={"instances": [{"input_ids": [16, 23, 42]}]},
                )
                assert r.status == 200
                return await (await client.get("/metrics")).text()

        text = asyncio.run(scrape())
        assert 'kubeflow_tpu_engine_completed{model="lm"}' in text
        assert 'kubeflow_tpu_engine_active_rows{model="lm"}' in text
    finally:
        m.unload()


def test_overload_sheds_with_429(model_and_params):
    """A full admission queue must answer 429, not queue unboundedly."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.engine import EngineOverloaded, LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer

    model, params = model_and_params
    m = LMEngineModel(
        "lm", None, config=CFG, max_batch=1, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=32, eos_id=EOS,
    )
    m.load()
    m._params = jax.device_put(params)
    m.engine.stop()
    m.engine = LMEngine(
        m._model, CFG, params, max_batch=1, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS, max_queue=1,
    ).start()
    server = ModelServer([m])

    async def drive():
        async with TestClient(TestServer(server.build_app())) as client:
            # saturate: 1 row busy + 1 queued + extras → some 429s
            posts = [
                client.post(
                    "/v1/models/lm:predict",
                    json={"instances": [{"input_ids": [3, 5, i + 2]}]},
                )
                for i in range(6)
            ]
            return [r.status for r in await asyncio.gather(*posts)]

    try:
        statuses = asyncio.run(drive())
    finally:
        m.unload()
    assert 200 in statuses          # the engine kept serving
    assert 429 in statuses, statuses  # and overload was shed, not queued
    # direct API: a FREE engine accepts even at max_queue=0; a busy one
    # sheds with the typed error
    eng2 = LMEngine(
        model, CFG, params, max_batch=1, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS, max_queue=0,
    ).start()
    try:
        bg = threading.Thread(
            target=lambda: eng2.submit([3, 4, 5], max_new_tokens=24)
        )
        bg.start()
        # wait until the row is actually occupied
        deadline = time.monotonic() + 120  # prefill compile under load
        while not any(s is not None for s in eng2._slots):
            assert time.monotonic() < deadline, "row never occupied"
            time.sleep(0.01)
        with pytest.raises(EngineOverloaded):
            eng2.submit([9, 9, 9], max_new_tokens=4)
        bg.join(60)
    finally:
        eng2.stop()


def test_stream_overload_is_429_before_headers(model_and_params):
    """generate_stream under overload must answer a clean 429 — never a
    200 SSE stream carrying an error frame."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.server import ModelServer

    model, params = model_and_params
    m = LMEngineModel(
        "lm", None, config=CFG, max_batch=1, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=48, eos_id=EOS,
    )
    m.load()
    m._params = jax.device_put(params)
    m.engine.stop()
    m.engine = LMEngine(
        m._model, CFG, params, max_batch=1, max_seq=96, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS, max_queue=0,
    ).start()
    server = ModelServer([m])

    # occupy the full capacity deterministically (a racing HTTP stream can
    # finish before the second request lands on a fast host)
    g1 = m.stream_row_tokens({"ids": [3, 5, 7], "temperature": 0.0})

    async def drive():
        async with TestClient(TestServer(server.build_app())) as client:
            r2 = await client.post(
                "/v2/models/lm/generate_stream", json={"input_ids": [9, 2]}
            )
            return r2.status

    try:
        # the overloaded stream sheds BEFORE committing a response: a clean
        # 429 status, not a 200 SSE stream carrying an error frame
        assert asyncio.run(drive()) == 429
        g1.close()
        # capacity released on close → streaming works again
        out = list(m.stream_row_tokens({"ids": [9, 2], "temperature": 0.0}))
        assert out and all(isinstance(c, list) for c in out)
    finally:
        m.unload()


def test_prefix_cache_exact_parity_and_reuse(model_and_params):
    """Prefix caching is a COMPUTE optimization, never a numerics change:
    completions with reused prefixes must equal the reference path exactly,
    and the stats must prove reuse actually happened."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=96, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS, prefix_cache_entries=4,
    ).start()
    try:
        rng = np.random.default_rng(11)
        system = [int(x) for x in rng.integers(2, CFG.vocab_size, size=20)]
        # first request stores system[:16] as a prefix entry
        first = system[:20]
        out1 = eng.submit(first, max_new_tokens=10)
        assert out1 == _reference_completion(model, params, first, 10)
        assert eng.stats["prefix_hits"] == 0
        # same 16-token prefix, different tails → every one must hit AND
        # match the from-scratch reference bit for bit
        for trial in range(3):
            tail = [int(x) for x in rng.integers(2, CFG.vocab_size, size=5)]
            ids = system[:16] + tail
            got = eng.submit(ids, max_new_tokens=10)
            want = _reference_completion(model, params, ids, 10)
            assert got == want, (trial, got, want)
        assert eng.stats["prefix_hits"] == 3
        assert eng.stats["prefix_tokens_reused"] == 48
    finally:
        eng.stop()


def test_prefix_cache_lru_eviction(model_and_params):
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=1, max_seq=96, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS, prefix_cache_entries=2,
    ).start()
    try:
        rng = np.random.default_rng(13)
        prompts = [
            [int(x) for x in rng.integers(2, CFG.vocab_size, size=18)]
            for _ in range(3)
        ]
        for p in prompts:  # three distinct 16-token prefixes, capacity 2
            eng.submit(p, max_new_tokens=4)
        assert len(eng._prefix_cache) == 2
        # oldest evicted → resubmitting prompt 0 gets NO hit; prompt 2 does
        eng.submit(prompts[0][:16] + [7, 8], max_new_tokens=4)
        assert eng.stats["prefix_hits"] == 0
        eng.submit(prompts[2][:16] + [7, 8], max_new_tokens=4)
        assert eng.stats["prefix_hits"] == 1
    finally:
        eng.stop()


def test_prefix_cache_respects_max_seq_fallback(model_and_params):
    """A hit whose reuse layout would overflow max_seq must fall back to a
    full prefill and still answer correctly."""
    model, params = model_and_params
    # a non-16-multiple bucket (20) makes the reuse layout (16 + 16 + 10 =
    # 42) exceed max_seq=40 while the full-prefill layout (20 + 10) fits
    eng = LMEngine(
        model, CFG, params, max_batch=1, max_seq=40, chunk_steps=4,
        prefill_buckets=(20,), eos_id=EOS, prefix_cache_entries=2,
    ).start()
    try:
        rng = np.random.default_rng(17)
        base = [int(x) for x in rng.integers(2, CFG.vocab_size, size=18)]
        eng.submit(base, max_new_tokens=4)  # stores base[:16]
        ids = base[:16] + [3, 4]
        got = eng.submit(ids, max_new_tokens=10)
        assert eng.stats["prefix_hits"] == 0  # fallback, not a broken hit
        # reference path uses bucket 32; engine used 20 — same numerics
        assert got == _reference_completion(model, params, ids, 10)
    finally:
        eng.stop()


def test_warmup_compiles_all_buckets_and_prefix_path(model_and_params):
    """After warmup with prefix caching on: every bucket's prefill, the
    implant/extract shapes, and the suffix prefill are compiled, and the
    warmup entries don't occupy the LRU."""
    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec

    model, params = model_and_params
    m = LMEngineModel(
        "lm", None, config=CFG, max_batch=2, chunk_steps=2, max_seq=96,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(16, 32)),
        max_new_tokens=8, eos_id=EOS, prefix_cache_entries=4,
    )
    m.load()
    try:
        m.warmup()
        eng = m.engine
        assert len(eng._prefix_cache) == 0  # no warmup pollution
        # the suffix warm covered the 16-multiple extract shapes — proof
        # the prefix path (implant/extract/suffix-prefill) compiled
        assert 16 in eng._extract_jits
        # a real shared-prefix workload immediately hits without compiling
        rng = np.random.default_rng(23)
        base = [int(x) for x in rng.integers(2, CFG.vocab_size, size=18)]
        out1 = m.engine.submit(base, max_new_tokens=6)
        out2 = m.engine.submit(base[:16] + [5, 6], max_new_tokens=6)
        assert eng.stats["prefix_hits"] >= 1
        assert out2 == _reference_completion(
            model, params, base[:16] + [5, 6], 6
        )
    finally:
        m.unload()


def test_prefix_cache_token_budget_eviction(model_and_params):
    """prefix_cache_tokens bounds TOTAL stored KV tokens (the HBM cost),
    evicting LRU entries — entry count alone would let memory scale with
    prefix length."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=1, max_seq=96, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS,
        prefix_cache_entries=64, prefix_cache_tokens=32,
    ).start()
    try:
        rng = np.random.default_rng(29)
        for _ in range(3):  # three 16-token entries against a 32 budget
            ids = [int(x) for x in rng.integers(2, CFG.vocab_size, size=18)]
            eng.submit(ids, max_new_tokens=4)
        assert eng._prefix_tokens_stored <= 32
        assert len(eng._prefix_cache) == 2
        assert sum(k * v for k, v in eng._prefix_lens.items()) == 32
    finally:
        eng.stop()


def test_tp_sharded_engine_matches_unsharded():
    """Tensor-parallel serving: an engine with params laid out by the
    training sharding rules over a model=2 mesh must produce the same
    tokens as the unsharded engine — TP is a layout, not a numerics
    change. (Dims chosen divisible by the model axis.)"""
    from jax.sharding import Mesh

    from kubeflow_tpu.parallel.sharding import transformer_rules

    cfg = TransformerConfig(
        vocab_size=96, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        causal=True, max_seq_len=256, attn_impl="reference",
        dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    devs = np.asarray(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("data", "model"))

    plain = LMEngine(
        model, cfg, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    sharded = LMEngine(
        model, cfg, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
        mesh=mesh, rules=transformer_rules(fsdp=False),
    ).start()
    try:
        # params really are sharded over the model axis
        q = sharded.params["layers_0"]["attn"]["q_proj"]["kernel"]
        assert "model" in str(q.sharding.spec)
        k0 = next(iter(sharded.cache.values()))["k"]
        assert "model" in str(k0.sharding.spec)
        rng = np.random.default_rng(31)
        for _ in range(3):
            ids = [int(x) for x in rng.integers(2, 96, size=rng.integers(4, 20))]
            a = plain.submit(ids, max_new_tokens=10)
            b = sharded.submit(ids, max_new_tokens=10)
            assert a == b, (ids, a, b)
    finally:
        plain.stop()
        sharded.stop()


def test_chunked_prefill_parity_and_interleaving(model_and_params):
    """prefill_chunk splits long prompts into pieces interleaved with
    decode — and changes NOTHING about the tokens produced, even with a
    concurrent request decoding mid-prefill."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=128, chunk_steps=2,
        prefill_buckets=(64,), eos_id=EOS, prefill_chunk=16,
    ).start()
    try:
        rng = np.random.default_rng(41)
        # single long prompt: 3 pieces (40 tokens / 16)
        ids = [int(x) for x in rng.integers(2, CFG.vocab_size, size=40)]
        got = eng.submit(ids, max_new_tokens=10)
        assert eng.stats["prefill_pieces"] == 3
        want = _reference_completion(model, params, ids, 10)
        assert got == want, (got, want)

        # a long admission arriving WHILE another row decodes: both match
        long_ids = [int(x) for x in rng.integers(2, CFG.vocab_size, size=48)]
        short_ids = [int(x) for x in rng.integers(2, CFG.vocab_size, size=6)]
        results = {}

        def run_short():
            results["short"] = eng.submit(short_ids, max_new_tokens=16)

        th = threading.Thread(target=run_short)
        th.start()
        time.sleep(0.02)  # short starts decoding first
        results["long"] = eng.submit(long_ids, max_new_tokens=10)
        th.join(120)
    finally:
        eng.stop()
    assert results["short"] == _reference_completion(
        model, params, short_ids, 16
    )
    assert results["long"] == _reference_completion(
        model, params, long_ids, 10
    )


def test_chunked_prefill_with_prefix_cache(model_and_params):
    """Chunked prefill composes with prefix caching: hit implants the
    prefix, the suffix chunks, answers stay exact."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=1, max_seq=160, chunk_steps=2,
        prefill_buckets=(64,), eos_id=EOS, prefill_chunk=16,
        prefix_cache_entries=4,
    ).start()
    try:
        rng = np.random.default_rng(43)
        base = [int(x) for x in rng.integers(2, CFG.vocab_size, size=50)]
        eng.submit(base, max_new_tokens=4)  # stores base[:48]
        tail = [int(x) for x in rng.integers(2, CFG.vocab_size, size=20)]
        ids = base[:48] + tail
        got = eng.submit(ids, max_new_tokens=10)
        assert eng.stats["prefix_hits"] == 1
        assert got == _reference_completion(model, params, ids, 10)
    finally:
        eng.stop()


def test_engine_with_gqa_model(model_and_params):
    """The engine serves a GQA config (half-size KV cache) with tokens
    equal to the whole-batch generate path."""
    del model_and_params  # GQA needs its own config/params
    cfg = TransformerConfig(
        vocab_size=89, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, causal=True, max_seq_len=256, attn_impl="reference",
        dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    eng = LMEngine(
        model, cfg, params, max_batch=2, max_seq=64, chunk_steps=4,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        assert next(iter(eng.cache.values()))["k"].shape[1] == 2
        gen = jax.jit(
            make_generate_fn(model, cfg, max_new_tokens=10, eos_id=EOS)
        )
        rng = np.random.default_rng(47)
        for _ in range(3):
            ids = [int(x) for x in rng.integers(2, 89, size=rng.integers(4, 20))]
            prompt = np.zeros((1, 32), np.int32)
            prompt[0, : len(ids)] = ids
            toks, n_valid = gen(
                params, prompt, np.asarray([len(ids)], np.int32),
                jax.random.PRNGKey(7), np.zeros((1,), np.float32),
            )
            want = [int(t) for t in np.asarray(toks)[0, : int(n_valid[0])]]
            assert eng.submit(ids, max_new_tokens=10) == want
    finally:
        eng.stop()


def test_engine_gqa_with_prefix_cache(model_and_params):
    """Prefix caching must extract/implant at the GQA cache's kv_heads
    width (regression: it sliced with n_heads and crashed)."""
    del model_and_params
    cfg = TransformerConfig(
        vocab_size=89, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, causal=True, max_seq_len=256, attn_impl="reference",
        dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    eng = LMEngine(
        model, cfg, params, max_batch=1, max_seq=96, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS, prefix_cache_entries=2,
    ).start()
    try:
        rng = np.random.default_rng(53)
        base = [int(x) for x in rng.integers(2, 89, size=20)]
        first = eng.submit(base, max_new_tokens=6)
        second = eng.submit(base[:16] + [7, 8], max_new_tokens=6)
        assert eng.stats["prefix_hits"] == 1
        gen = jax.jit(
            make_generate_fn(model, cfg, max_new_tokens=6, eos_id=EOS)
        )
        for ids, got in ((base, first), (base[:16] + [7, 8], second)):
            prompt = np.zeros((1, 32), np.int32)
            prompt[0, : len(ids)] = ids
            toks, n_valid = gen(
                params, prompt, np.asarray([len(ids)], np.int32),
                jax.random.PRNGKey(7), np.zeros((1,), np.float32),
            )
            assert got == [int(t) for t in np.asarray(toks)[0, : int(n_valid[0])]]
    finally:
        eng.stop()


# ------------------------------------------------- pipelined decode (carry)


@pytest.mark.slow
def test_pipelined_inline_token_parity_under_churn(model_and_params):
    """The tentpole contract: pipeline_depth=1 (device-resident carry +
    one-chunk-ahead dispatch) emits byte-identical token streams to the
    inline pipeline_depth=0 path for the same seed, under admission churn
    (7 staggered requests through 3 rows), chunked prefill (prefill_chunk
    splits the long prompts), and a mid-stream cancellation."""
    model, params = model_and_params
    rng = np.random.default_rng(71)
    # mixed lengths: several short, two long enough for multi-piece prefill
    prompts = _prompts(rng, 5, lo=3, hi=14) + [
        [int(x) for x in rng.integers(2, CFG.vocab_size, size=n)]
        for n in (34, 41)
    ]

    def run_mode(depth):
        eng = LMEngine(
            model, CFG, params, max_batch=3, max_seq=96, chunk_steps=4,
            prefill_buckets=(48,), eos_id=EOS, prefill_chunk=16, seed=7,
            pipeline_depth=depth,
        ).start()
        outs: dict[int, list[int]] = {}
        errors: list[Exception] = []

        def worker(i):
            try:
                time.sleep(0.02 * i)  # staggered arrivals → admission churn
                outs[i] = eng.submit(prompts[i], max_new_tokens=12)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        try:
            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            # mid-stream cancellation riding along: read one chunk, walk away
            stream = eng.stream(prompts[0], max_new_tokens=12)
            next(iter(stream))
            stream.close()
            for t in threads:
                t.join(180)
            stats = dict(eng.stats)
            uploads = eng.overlap["carry_uploads"]
        finally:
            eng.stop()
        assert not errors, errors
        return outs, stats, uploads

    pipe, pipe_stats, pipe_uploads = run_mode(1)
    inline, _, _ = run_mode(0)
    assert len(pipe) == len(prompts)
    for i in range(len(prompts)):
        assert pipe[i] == inline[i], (i, pipe[i], inline[i])
        # and both equal the pinned whole-batch reference (greedy)
        want = _reference_completion(model, params, prompts[i], 12)
        assert pipe[i] == want, (i, pipe[i], want)
    assert pipe_stats["max_concurrent"] >= 2  # churn really happened
    assert pipe_stats["prefill_pieces"] > len(prompts)  # chunked prefills ran
    # epochs, not chunks: uploads bounded by admissions/activations, far
    # below one per chunk once decode is the steady state
    assert pipe_uploads < pipe_stats["chunks"] + 2 * pipe_stats["admitted"]


def test_pipelined_steady_state_uploads_are_epochs_not_chunks(
    model_and_params,
):
    """Acceptance: steady-state decode performs ZERO per-chunk H2D of the
    per-row arrays — carry uploads grow only on admit/retire/prefill
    epochs. Finds a request whose decode spans several chunks and shows
    its upload delta stays at the admission epoch alone."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS, pipeline_depth=1,
    ).start()
    try:
        rng = np.random.default_rng(73)
        found = False
        for ids in _prompts(rng, 40):
            c0 = eng.stats["chunks"]
            u0 = eng.overlap["carry_uploads"]
            out = eng.submit(ids, max_new_tokens=16)
            dc = eng.stats["chunks"] - c0
            du = eng.overlap["carry_uploads"] - u0
            # every submit is one admission epoch (single-piece prefill):
            # one upload, regardless of how many chunks it decoded for
            assert du <= 2, (ids, du, dc)
            if len(out) >= 10:  # ≥5 chunks at chunk_steps=2
                assert dc > du, (ids, dc, du)
                found = True
                break
        assert found, "no prompt produced a long enough completion"
    finally:
        eng.stop()


def test_pipelined_fatal_inflight_chunk_cannot_leak_requests(
    model_and_params,
):
    """If the device dies while a speculative chunk is in flight, every
    request — including those whose freshest tokens only exist in the
    undrained chunk — must fail promptly with the real error, and later
    submits fail fast. No wedged request, no silent dead scheduler."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS, pipeline_depth=1,
    ).start()
    real_chunk = eng._chunk
    calls = {"n": 0}

    def exploding(*a, **k):
        calls["n"] += 1
        if calls["n"] >= 2:  # chunk 1 dispatches fine and stays in flight
            raise RuntimeError("injected device failure")
        return real_chunk(*a, **k)

    eng._chunk = exploding
    errors: dict[int, Exception] = {}

    def worker(i):
        try:
            eng.submit([3 + i, 5, 7, 11], max_new_tokens=16, timeout_s=30)
        except Exception as e:  # noqa: BLE001
            errors[i] = e

    try:
        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(2)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(25)
        assert all(not t.is_alive() for t in threads)
        assert time.monotonic() - t0 < 20  # prompt failure, not a timeout
        assert len(errors) == 2, "a request leaked past the fatal path"
        for e in errors.values():
            assert "injected device failure" in str(e)
        with pytest.raises(RuntimeError, match="engine is dead"):
            eng.submit([9, 9, 9], max_new_tokens=4, timeout_s=10)
    finally:
        eng.stop()


def test_idle_parks_without_busy_wake(model_and_params):
    """The idle path must PARK on the work event, not poll at 20 Hz: over
    an idle second the wake-count probe stays flat, and a submit still
    wakes the loop immediately."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=1, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        eng.submit([3, 4, 5], max_new_tokens=4)  # compile + settle
        time.sleep(0.1)  # let the loop reach the park branch
        wakes0 = eng.stats["idle_wakes"]
        time.sleep(1.2)
        # the old 0.05s poll would add ~24 park entries here
        assert eng.stats["idle_wakes"] - wakes0 <= 2
        # and the event wake path still serves promptly
        t0 = time.monotonic()
        out = eng.submit([5, 6, 7], max_new_tokens=4, timeout_s=30)
        assert isinstance(out, list)
        assert time.monotonic() - t0 < 5.0
    finally:
        eng.stop()


def test_carry_upload_never_aliases_host_mirrors(model_and_params):
    """Regression (CPU backend): jnp.asarray of an aligned numpy buffer
    is ZERO-COPY, so an un-snapshotted carry upload aliases the live
    host mirrors — a later in-place host edit (prefill activation, drain
    refresh) retroactively rewrites what an in-flight chunk reads. That
    raced as chunked-prefill rows truncating to their first token under
    churn. The carry (and the paged device table) must be immune to
    mirror mutation after upload."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    )
    eng.last_tok[:] = 7
    eng.active[:] = False
    eng._upload_carry()
    c = eng._carry
    eng.last_tok[:] = 99   # host edit AFTER upload
    eng.active[:] = True
    assert list(np.asarray(c["last_tok"])) == [7, 7]
    assert list(np.asarray(c["active"])) == [False, False]
    # paged block-table mirror: same invariant through the memo
    from kubeflow_tpu.serve.paging import PageAllocator

    pager = PageAllocator(
        pool_tokens=16 * 8, page_size=16, max_batch=2, max_pages_per_row=4
    )
    pager.alloc(0, 2)
    dev = pager.device_table(4)
    before = np.asarray(dev).copy()
    pager.free(0)
    pager.alloc(1, 3)
    assert (np.asarray(dev) == before).all()


def test_engine_config_object_and_depth_validation(model_and_params):
    """LMEngineConfig bundles the knobs; unknown overrides and invalid
    pipeline depths fail loudly."""
    from kubeflow_tpu.serve.engine import LMEngineConfig

    model, params = model_and_params
    cfgobj = LMEngineConfig(
        max_batch=2, max_seq=64, chunk_steps=4, prefill_buckets=(32,),
        eos_id=EOS, pipeline_depth=0,
    )
    eng = LMEngine(model, CFG, params, config=cfgobj).start()
    try:
        assert eng.pipeline_depth == 0
        ids = [5, 9, 33, 60]
        assert eng.submit(ids, max_new_tokens=6) == _reference_completion(
            model, params, ids, 6
        )
    finally:
        eng.stop()
    with pytest.raises(ValueError, match="pipeline_depth"):
        LMEngine(model, CFG, params, max_batch=2, pipeline_depth=2)
    with pytest.raises(TypeError):
        LMEngine(model, CFG, params, not_a_knob=1)


def test_engine_with_sliding_window(model_and_params):
    """A sliding-window model served through the engine must produce the
    batch path's answers (which window via reference_attention) — exercises
    the windowed chunk-decode kv_mask, the windowed suffix-prefill default
    mask, and prefix reuse under a window."""
    import dataclasses

    wcfg = dataclasses.replace(CFG, attn_window=4)
    model = TransformerLM(wcfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    gen = jax.jit(
        make_generate_fn(model, wcfg, max_new_tokens=12, eos_id=EOS)
    )

    def want_for(ids):
        prompt = np.zeros((1, 32), np.int32)
        prompt[0, : len(ids)] = ids
        toks, n_valid = gen(
            params, prompt, np.asarray([len(ids)], np.int32),
            jax.random.PRNGKey(7), np.zeros((1,), np.float32),
        )
        return [int(t) for t in np.asarray(toks)[0, : int(n_valid[0])]]

    eng = LMEngine(
        model, wcfg, params, max_batch=3, max_seq=64, chunk_steps=3,
        prefill_buckets=(32,), eos_id=EOS, prefix_cache_entries=4,
    ).start()
    try:
        rng = np.random.default_rng(5)
        # prompts LONGER than the window so the boundary is live
        prompts = [
            [int(x) for x in rng.integers(2, CFG.vocab_size, size=n)]
            for n in (6, 9, 17)
        ]
        for ids in prompts:
            assert eng.submit(ids, max_new_tokens=12) == want_for(ids)
        # resubmit the longest prompt: prefix reuse + windowed suffix prefill
        before = eng.stats["prefix_hits"]
        assert eng.submit(prompts[2], max_new_tokens=12) == want_for(prompts[2])
        assert eng.stats["prefix_hits"] > before
    finally:
        eng.stop()


# ------------------------------------------- mid-stream failover resume


def test_engine_resume_tokens_continue_greedy_identically(model_and_params):
    """The resume contract: admitting prompt+committed with a shrunk
    budget emits exactly the tokens an uninterrupted run would have
    produced past the committed prefix — the engine half of the gateway's
    transparent mid-stream failover."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=4, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        rng = np.random.default_rng(11)
        for ids in _prompts(rng, 4):
            full = eng.submit(ids, max_new_tokens=10)
            if len(full) < 3:
                continue  # EOS too early to split meaningfully
            for cut in (1, len(full) // 2, len(full) - 1):
                admits0 = eng.stats["resume_admits"]
                rest = eng.submit(
                    ids, max_new_tokens=10, resume_tokens=full[:cut]
                )
                assert rest == full[cut:], (ids, cut, rest, full)
                assert eng.stats["resume_admits"] == admits0 + 1
    finally:
        eng.stop()


def test_engine_seeded_sampling_deterministic_and_resumable(model_and_params):
    """Seeded temperature>0 draws: token t comes from
    fold_in(PRNGKey(seed), position_of_t), so (a) two runs with the same
    seed agree, (b) a resumed run continues the exact sampling stream,
    and (c) a different seed diverges (the draws are real, not greedy)."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=4, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        ids = [5, 9, 33, 60, 7]
        kw = dict(max_new_tokens=10, temperature=0.9)
        a = eng.submit(ids, seed=1234, **kw)
        b = eng.submit(ids, seed=1234, **kw)
        assert a == b, (a, b)
        if len(a) >= 3:
            cut = len(a) // 2
            rest = eng.submit(ids, seed=1234, resume_tokens=a[:cut], **kw)
            assert rest == a[cut:], (a, cut, rest)
        # a distinct seed must be able to diverge somewhere
        others = [eng.submit(ids, seed=s, **kw) for s in (77, 78, 79)]
        assert any(o != a for o in others), (a, others)
        # unseeded requests still ride the legacy engine-RNG path
        assert eng.submit(ids, max_new_tokens=6) == eng.submit(
            ids, max_new_tokens=6
        )
    finally:
        eng.stop()


def test_engine_resume_validation_errors(model_and_params):
    """A resume prefix that exhausts the budget, or that already contains
    EOS, is a caller error rejected at admission — never a row wasted."""
    model, params = model_and_params
    eng = LMEngine(
        model, CFG, params, max_batch=2, max_seq=64, chunk_steps=2,
        prefill_buckets=(32,), eos_id=EOS,
    ).start()
    try:
        with pytest.raises(ValueError, match="no generation budget"):
            eng.submit([5, 6, 7], max_new_tokens=3, resume_tokens=[8, 9, 10])
        with pytest.raises(ValueError, match="EOS"):
            eng.submit([5, 6, 7], max_new_tokens=8, resume_tokens=[8, EOS])
        # boundary: resume leaving exactly one token of budget is admitted
        out = eng.submit([5, 6, 7], max_new_tokens=3, resume_tokens=[8, 9])
        assert len(out) <= 1
    finally:
        eng.stop()
