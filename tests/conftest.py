"""Test harness config: 8 virtual CPU devices, per SURVEY.md §4.

The reference validates its whole multi-node story without real accelerators
(envtest + gloo-on-kind); our analog is JAX's CPU backend with
``xla_force_host_platform_device_count=8`` giving a faked 8-device mesh in
one process. MUST run before the first ``import jax`` anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Scrub the accelerator-plugin trigger for this process AND everything it
# spawns (CLI serve subprocesses, e2e gangs): the image's sitecustomize
# registers the tunneled-TPU plugin whenever PALLAS_AXON_POOL_IPS is set,
# and when the tunnel wedges that registration BLOCKS at interpreter
# startup even under JAX_PLATFORMS=cpu. The CPU suite must never depend
# on tunnel health. (envwire.py does the same for launcher children.)
for _k in [k for k in os.environ if k.startswith("PALLAS_AXON")]:
    os.environ.pop(_k)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# This image's sitecustomize imports jax at interpreter startup (with
# JAX_PLATFORMS=axon already in the env), so jax.config captured 'axon'
# before this file ran — override through the config API as well.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


def wait_for_job_step(cluster, uid, step, timeout=240):
    """Poll worker-0 stdout until ``step=N`` appears (any attempt) —
    shared by the elastic and autoscaler e2e tests."""
    import time as _time

    from kubeflow_tpu.train.metrics import parse_stdout_metrics

    deadline = _time.time() + timeout
    while _time.time() < deadline:
        if any(
            m["step"] >= step
            for m in parse_stdout_metrics(cluster.logs(uid, "worker", 0))
        ):
            return
        if cluster.status(uid).finished:
            raise AssertionError(
                f"job finished before reaching step {step}:\n"
                + cluster.logs(uid, "worker", 0)
            )
        _time.sleep(0.2)
    raise TimeoutError(
        f"step {step} not reached; log:\n" + cluster.logs(uid, "worker", 0)
    )
