"""Attention kernels: Pallas flash (interpret mode), ring CP, Ulysses SP.

Numerics oracle is plain-XLA reference_attention; kernels run in interpret
mode on the virtual CPU mesh (compiled-mode parity is exercised on the real
chip by bench/serving paths).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.core.mesh import Axis, MeshSpec, build_mesh
from kubeflow_tpu.ops.flash_attention import flash_attention, reference_attention
from kubeflow_tpu.parallel.ring_attention import ring_attention
from kubeflow_tpu.parallel.ulysses import ulysses_attention

B, H, S, D = 2, 8, 256, 32


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D) / np.sqrt(D), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(qkv, causal):
    q, k, v = qkv
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_segment_masking(qkv):
    q, k, v = qkv
    rng = np.random.RandomState(1)
    seg = jnp.asarray(np.sort(rng.randint(0, 3, (B, S)), axis=-1))
    out = flash_attention(
        q, k, v, q_segment_ids=seg, kv_segment_ids=seg, interpret=True
    )
    ref = reference_attention(q, k, v, q_segment_ids=seg, kv_segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_grad_matches_reference(qkv):
    q, k, v = qkv

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, interpret=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_flash_grad_with_segments_matches_reference(qkv):
    # the Pallas backward kernels must respect segment masking (packed
    # sequences): masked entries contribute exactly zero gradient
    q, k, v = qkv
    rng = np.random.RandomState(2)
    seg = jnp.asarray(np.sort(rng.randint(0, 3, (B, S)), axis=-1))

    def loss_flash(q, k, v):
        return (
            flash_attention(
                q, k, v, q_segment_ids=seg, kv_segment_ids=seg, interpret=True
            )
            ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            reference_attention(q, k, v, q_segment_ids=seg, kv_segment_ids=seg)
            ** 2
        ).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_flash_rejects_bad_shapes(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="heads"):
        flash_attention(q[:, :4], k, v, interpret=True)
    with pytest.raises(ValueError, match="segment"):
        flash_attention(q, k, v, q_segment_ids=jnp.zeros((B, S), jnp.int32))
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q[:, :, :150], k[:, :, :150], v[:, :, :150],
                        block_q=128, block_k=128, interpret=True)


# ------------------------- ring attention (CP) ------------------------- #

@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(qkv, causal, devices8):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(seq=8))
    out = ring_attention(q, k, v, mesh, causal=causal, interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grad(qkv, causal, devices8):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(seq=4), devices=jax.devices()[:4])

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=causal, interpret=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=causal) ** 2).sum()

    gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4,
            err_msg=f"d{name} mismatch (causal={causal})",
        )


def test_ring_attention_2d_mesh(qkv, devices8):
    """seq ring composed with data-parallel batch sharding."""
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(data=2, seq=4))
    out = ring_attention(q, k, v, mesh, causal=True, interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


# ------------------------- Ulysses (SP) -------------------------------- #

@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(qkv, causal, devices8):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(seq=8))
    out = ulysses_attention(q, k, v, mesh, causal=causal, interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_rejects_indivisible_heads(qkv, devices8):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(seq=8))
    with pytest.raises(Exception, match="divisible|Ulysses"):
        ulysses_attention(q[:, :6], k[:, :6], v[:, :6], mesh, interpret=True)


# ---------------- packed sequences (segment ids) over CP/SP ------------ #

@pytest.fixture(scope="module")
def packed_segs():
    """(B, S) segment labels: three packed documents per row."""
    rng = np.random.RandomState(3)
    seg = np.zeros((B, S), np.int32)
    for b in range(B):
        cuts = sorted(rng.choice(np.arange(8, S - 8), size=2, replace=False))
        seg[b, cuts[0]:cuts[1]] = 1
        seg[b, cuts[1]:] = 2
    return jnp.asarray(seg)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_segment_ids(qkv, packed_segs, causal, devices8):
    """Packed-sequence masking rides the ring: cross-document attention is
    blocked exactly as in the reference, across shard boundaries."""
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(seq=8))
    out = ring_attention(
        q, k, v, mesh, causal=causal, segment_ids=packed_segs, interpret=True
    )
    ref = reference_attention(
        q, k, v, causal=causal,
        q_segment_ids=packed_segs, kv_segment_ids=packed_segs,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_segment_ids_grad(qkv, packed_segs, devices8):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(seq=4), devices=jax.devices()[:4])

    def loss_ring(q, k, v):
        return (
            ring_attention(
                q, k, v, mesh, causal=True, segment_ids=packed_segs,
                interpret=True,
            ) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            reference_attention(
                q, k, v, causal=True,
                q_segment_ids=packed_segs, kv_segment_ids=packed_segs,
            ) ** 2
        ).sum()

    gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4,
            err_msg=f"d{name} mismatch (segmented ring)",
        )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_segment_ids(qkv, packed_segs, causal, devices8):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(seq=8))
    out = ulysses_attention(
        q, k, v, mesh, causal=causal, segment_ids=packed_segs, interpret=True
    )
    ref = reference_attention(
        q, k, v, causal=causal,
        q_segment_ids=packed_segs, kv_segment_ids=packed_segs,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_segment_ids_grad(qkv, packed_segs, devices8):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(seq=8))

    def loss_uly(q, k, v):
        return (
            ulysses_attention(
                q, k, v, mesh, causal=True, segment_ids=packed_segs,
                interpret=True,
            ) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            reference_attention(
                q, k, v, causal=True,
                q_segment_ids=packed_segs, kv_segment_ids=packed_segs,
            ) ** 2
        ).sum()

    gf = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4,
            err_msg=f"d{name} mismatch (segmented ulysses)",
        )


# ---------------------- sliding-window attention ----------------------- #

@pytest.mark.parametrize("window", [16, 40, 128])
def test_flash_sliding_window_matches_reference(qkv, window):
    q, k, v = qkv
    out = flash_attention(
        q, k, v, causal=True, window=window, block_q=16, block_k=16,
        interpret=True,
    )
    ref = reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_sliding_window_grad(qkv):
    q, k, v = qkv
    window = 24

    def loss_flash(q, k, v):
        return (
            flash_attention(
                q, k, v, causal=True, window=window, block_q=16,
                block_k=16, interpret=True,
            ) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            reference_attention(q, k, v, causal=True, window=window) ** 2
        ).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4,
            err_msg=f"d{name} mismatch (window={window})",
        )


def test_flash_window_with_segments(qkv, packed_segs):
    """Window and packed-sequence masks compose."""
    q, k, v = qkv
    out = flash_attention(
        q, k, v, causal=True, window=24,
        q_segment_ids=packed_segs, kv_segment_ids=packed_segs,
        block_q=16, block_k=16, interpret=True,
    )
    ref = reference_attention(
        q, k, v, causal=True, window=24,
        q_segment_ids=packed_segs, kv_segment_ids=packed_segs,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_window_requires_causal(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=8, interpret=True)


# --------------------------------------------------------- block selection


@pytest.mark.parametrize("blocks", [(128, 256), (256, 128), (256, 256),
                                    (128, 512), (512, 512)])
def test_flash_nondefault_blocks_match_reference(blocks):
    """Every candidate block shape the S512 tuner sweeps must be
    numerically identical to reference — fwd AND grad — so the sweep can
    pick purely on speed (interpret mode exercises the same tile code)."""
    bq, bk = blocks
    B, H, S, D = 1, 2, 512, 32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, H, S, D), jnp.float32) for kk in ks)
    out = flash_attention(
        q, k, v, causal=True, block_q=bq, block_k=bk, interpret=True
    )
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )

    def f_loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    g_flash = jax.grad(
        f_loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=bq, block_k=bk, interpret=True
        ))
    )(q, k, v)
    g_ref = jax.grad(
        f_loss(lambda q, k, v: reference_attention(q, k, v, causal=True))
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g_flash), np.asarray(g_ref), rtol=5e-3, atol=5e-3
    )


def test_block_selection_table_and_heuristic(tmp_path, monkeypatch):
    from kubeflow_tpu.ops import flash_tuning as ft

    # no table: heuristic — 128x128 short, wider K at 256+
    monkeypatch.setenv("KFT_FLASH_BLOCKS_FILE", str(tmp_path / "none.json"))
    ft.reset_table_cache()
    assert ft.select_blocks(128, 128, 64) == (128, 128)
    assert ft.select_blocks(512, 512, 64) == (128, 256)
    # big head_dim stays conservative (tile bytes scale with D)
    assert ft.select_blocks(512, 512, 256) == (128, 128)
    # block sizes divide the sequence when a sane divisor exists
    assert ft.select_blocks(96, 96, 64) == (96, 96)
    assert ft.select_blocks(384, 384, 64) == (128, 192)
    # prime-ish lengths must NOT degrade to block-1 grids — selection
    # keeps a non-dividing cap so the kernel's explicit 'pad inputs'
    # divisibility error fires instead
    bq, bk = ft.select_blocks(509, 509, 64)
    assert bq > 1 and bk > 1 and (509 % bq and 509 % bk)
    q = jnp.zeros((1, 1, 509, 64), jnp.float32)
    with pytest.raises(ValueError, match="pad inputs"):
        flash_attention(q, q, q, causal=True, block_q=None, block_k=None,
                        interpret=True)

    # a measured table wins (keyed by seq bucket AND head_dim)
    (tmp_path / "t.json").write_text('{"512:64": [256, 512]}')
    monkeypatch.setenv("KFT_FLASH_BLOCKS_FILE", str(tmp_path / "t.json"))
    ft.reset_table_cache()
    assert ft.select_blocks(512, 512, 64) == (256, 512)
    assert ft.select_blocks(512, 512, 128) == (128, 256)  # other D: heuristic
    # the table's bucket entry still adapts to non-dividing shapes
    assert ft.select_blocks(384, 384, 64) == (192, 384)
    ft.reset_table_cache()


def test_flash_auto_blocks_parity():
    """block_q=None routes through select_blocks and stays exact."""
    B, H, S, D = 1, 2, 256, 32
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (B, H, S, D), jnp.float32) for kk in ks)
    out = flash_attention(
        q, k, v, causal=True, block_q=None, block_k=None, interpret=True
    )
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )
