"""WordPiece tokenizer: parity against transformers.BertTokenizer on the
same vocab.txt (the ids a reference user's checkpoint was trained with),
plus the serving integration — an HF-format model dir with a vocab must be
tokenized with it, and a corrupt checkpoint must fail closed instead of
serving random weights (VERDICT r1 items 3 and weak-4)."""

import json
from pathlib import Path

import numpy as np
import pytest

from kubeflow_tpu.serve.tokenizer import WordPieceTokenizer, load_vocab

VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over",
    "lazy", "dog", "un", "##want", "runn", "##ing", "hello",
    "world", ",", ".", "!", "?", "'", "s", "##iz", "##ation",
    "token", "我", "是",
]


@pytest.fixture()
def vocab_file(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    return p


TEXTS = [
    "The quick brown fox jumped over the lazy dog.",
    "unwanted running",
    "Hello, world! tokenization?",
    "the fox's dog",
    "zebra quantum",                      # unknown words -> [UNK]
    "Crème brûlée the fox",               # accent stripping
    "hello 我是 world",                    # CJK isolation
    "the [MASK] dog",                     # mask must survive whole
    "",                                   # empty text
]


def test_parity_with_transformers(vocab_file):
    transformers = pytest.importorskip("transformers")
    theirs = transformers.BertTokenizer(
        str(vocab_file), do_lower_case=True, do_basic_tokenize=True
    )
    ours = WordPieceTokenizer(vocab_file)
    for text in TEXTS:
        assert ours.tokenize(text) == theirs.tokenize(text), text
        assert ours.encode(text) == theirs.encode(text), text


def test_pair_encoding(vocab_file):
    t = WordPieceTokenizer(vocab_file)
    ids = t.encode("the fox", "the dog")
    # [CLS] the fox [SEP] the dog [SEP]
    assert ids[0] == t.cls_id
    assert ids.count(t.sep_id) == 2
    assert ids[-1] == t.sep_id


def test_decode_roundtrip(vocab_file):
    t = WordPieceTokenizer(vocab_file)
    ids = t.encode("unwanted running")
    assert t.decode(ids) == "unwanted running"


def test_special_token_ids_from_vocab(vocab_file):
    t = WordPieceTokenizer(vocab_file)
    v = load_vocab(vocab_file)
    assert t.cls_id == v["[CLS]"]
    assert t.mask_id == v["[MASK]"]
    assert t.encode("the [MASK] dog")[2] == t.mask_id


def test_missing_required_token(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("[UNK]\nfoo\n")
    with pytest.raises(ValueError, match="CLS"):
        WordPieceTokenizer(p)


# --------------------------------------------------------------------- #
# serving integration
# --------------------------------------------------------------------- #


def _hf_bert_dir(tmp_path: Path):
    """Tiny HF-format dir: config.json + pytorch_model.bin + vocab.txt."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    cfg = transformers.BertConfig(
        vocab_size=len(VOCAB),
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
        type_vocab_size=2,
    )
    torch.manual_seed(0)
    model = transformers.BertModel(cfg)
    d = tmp_path / "model"
    d.mkdir()
    (d / "config.json").write_text(json.dumps(cfg.to_dict()))
    torch.save(model.state_dict(), d / "pytorch_model.bin")
    (d / "vocab.txt").write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    return d


def test_bert_runtime_uses_checkpoint_vocab(tmp_path, devices8):
    transformers = pytest.importorskip("transformers")
    from kubeflow_tpu.models.convert import bert_config_from_hf
    from kubeflow_tpu.serve.model import BucketSpec
    from kubeflow_tpu.serve.runtimes import BertRuntimeModel

    d = _hf_bert_dir(tmp_path)
    cfg = bert_config_from_hf(
        json.loads((d / "config.json").read_text()), attn_impl="reference"
    )
    m = BertRuntimeModel(
        "bert", str(d), config=cfg,
        buckets=BucketSpec(batch_sizes=(1, 2), seq_lens=(16,)),
    )
    theirs = transformers.BertTokenizer(str(d / "vocab.txt"))
    text = "the quick brown fox"
    rows = m.preprocess({"instances": [text]})
    assert rows[0].tolist() == theirs.encode(text)
    assert m.load()
    out = m.predict(rows)
    assert np.asarray(out).shape[0] == 1


def test_bert_runtime_fails_closed_on_corrupt_checkpoint(tmp_path, devices8):
    from kubeflow_tpu.models.bert import bert_tiny
    from kubeflow_tpu.serve.runtimes import BertRuntimeModel

    bad = tmp_path / "ckpt"
    bad.mkdir()
    (bad / "garbage.bin").write_bytes(b"\x00not-a-checkpoint")
    m = BertRuntimeModel(
        "bert", str(bad), config=bert_tiny(attn_impl="reference")
    )
    with pytest.raises(Exception):
        m.load()
    assert not m.ready


def test_bert_runtime_fails_closed_on_missing_dir(tmp_path, devices8):
    from kubeflow_tpu.models.bert import bert_tiny
    from kubeflow_tpu.serve.runtimes import BertRuntimeModel

    m = BertRuntimeModel(
        "bert", str(tmp_path / "nope"), config=bert_tiny(attn_impl="reference")
    )
    with pytest.raises(RuntimeError, match="missing or empty"):
        m.load()
    assert not m.ready

    empty = tmp_path / "empty"
    empty.mkdir()
    m2 = BertRuntimeModel(
        "bert", str(empty), config=bert_tiny(attn_impl="reference")
    )
    with pytest.raises(RuntimeError, match="missing or empty"):
        m2.load()


def test_bert_runtime_respects_tokenizer_config_casing(tmp_path, devices8):
    d = _hf_bert_dir(tmp_path)
    (d / "tokenizer_config.json").write_text('{"do_lower_case": false}')
    import json as _json

    from kubeflow_tpu.models.convert import bert_config_from_hf
    from kubeflow_tpu.serve.runtimes import BertRuntimeModel

    cfg = bert_config_from_hf(
        _json.loads((d / "config.json").read_text()), attn_impl="reference"
    )
    m = BertRuntimeModel("bert", str(d), config=cfg)
    assert m.tokenizer.do_lower_case is False
