"""Native data plane (SURVEY.md §2.8 obligation): C++ record loader built
from source, exercised through the ctypes boundary, checked against the
pure-Python fallback for identical semantics."""

import numpy as np
import pytest

from kubeflow_tpu.data.records import (
    PyRecordLoader,
    RecordLoader,
    RecordSpec,
    ensure_built,
    native_available,
    write_records,
    write_records_py,
)

SPEC = RecordSpec.of(
    image=("float32", (4, 4)),
    label=("int32", ()),
    idx=("int64", ()),
)


def _dataset(n, start=0):
    rng = np.random.RandomState(7 + start)
    return {
        "image": rng.randn(n, 4, 4).astype(np.float32),
        "label": rng.randint(0, 10, size=n).astype(np.int32),
        "idx": np.arange(start, start + n, dtype=np.int64),
    }


@pytest.fixture(scope="module")
def built():
    ensure_built()
    assert native_available()


def _write_files(tmp_path, per_file=(30, 25), writer=write_records_py):
    files, start = [], 0
    for i, n in enumerate(per_file):
        p = tmp_path / f"part-{i}.kftr"
        writer(p, SPEC, _dataset(n, start))
        files.append(p)
        start += n
    return files, start


def test_pack_unpack_roundtrip():
    data = _dataset(6)
    packed = SPEC.pack(data)
    assert packed.shape == (6, SPEC.record_bytes)
    out = SPEC.unpack(packed, 6)
    np.testing.assert_array_equal(out["image"], data["image"])
    np.testing.assert_array_equal(out["label"], data["label"])
    np.testing.assert_array_equal(out["idx"], data["idx"])


def test_native_writer_matches_python_writer(tmp_path, built):
    data = _dataset(11)
    write_records(tmp_path / "n.kftr", SPEC, data)
    write_records_py(tmp_path / "p.kftr", SPEC, data)
    assert (tmp_path / "n.kftr").read_bytes() == (tmp_path / "p.kftr").read_bytes()


def test_native_loader_sees_every_record_once(tmp_path, built):
    files, total = _write_files(tmp_path)
    seen = []
    with RecordLoader(
        files, SPEC, batch_size=8, shuffle_records=16, seed=3,
        drop_remainder=False,
    ) as loader:
        for batch in loader:
            assert batch["image"].dtype == np.float32
            seen.extend(batch["idx"].tolist())
    assert sorted(seen) == list(range(total))  # exactly-once per epoch
    assert seen != list(range(total))  # and actually shuffled


def test_native_loader_drop_remainder_and_determinism(tmp_path, built):
    files, total = _write_files(tmp_path)

    def run(seed):
        out = []
        with RecordLoader(
            files, SPEC, batch_size=8, shuffle_records=16, seed=seed
        ) as loader:
            for b in loader:
                assert len(b["idx"]) == 8  # drop_remainder=True default
                out.extend(b["idx"].tolist())
        return out

    a, b2 = run(5), run(5)
    assert a == b2  # same seed → same order
    assert run(6) != a  # different seed → different order
    assert len(a) == (total // 8) * 8


def test_native_loader_sharding_partitions(tmp_path, built):
    files, total = _write_files(tmp_path)
    shards = []
    for i in range(3):
        seen = []
        with RecordLoader(
            files, SPEC, batch_size=4, shard_index=i, shard_count=3,
            drop_remainder=False,
        ) as loader:
            for b in loader:
                seen.extend(b["idx"].tolist())
        shards.append(set(seen))
    assert set().union(*shards) == set(range(total))
    assert sum(len(s) for s in shards) == total  # disjoint cover


def test_native_loader_multi_epoch(tmp_path, built):
    files, total = _write_files(tmp_path, per_file=(10,))
    seen = []
    with RecordLoader(
        files, SPEC, batch_size=5, epochs=3, drop_remainder=False
    ) as loader:
        for b in loader:
            seen.extend(b["idx"].tolist())
    assert len(seen) == 3 * total


def test_native_loader_rejects_bad_input(tmp_path, built):
    bad = tmp_path / "bad.kftr"
    bad.write_bytes(b"garbage-not-a-header")
    with pytest.raises(OSError, match="bad header"):
        loader = RecordLoader([bad], SPEC, batch_size=2)
        next(loader)
    with pytest.raises(OSError, match="shard_index"):
        RecordLoader(
            [bad], SPEC, batch_size=2, shard_index=5, shard_count=2
        )


def test_batches_survive_next_call(tmp_path, built):
    """A held batch must not be overwritten by the following one (the fill
    buffer is reused internally; returned arrays must be private)."""
    files, _ = _write_files(tmp_path, per_file=(16,))
    single = RecordSpec.of(idx=("int64", ()))  # single-field: worst case
    sfiles = [tmp_path / "s.kftr"]
    write_records_py(sfiles[0], single, {"idx": np.arange(16, dtype=np.int64)})
    with RecordLoader(sfiles, single, batch_size=4) as loader:
        first = next(loader)["idx"]
        snapshot = first.copy()
        next(loader)
        np.testing.assert_array_equal(first, snapshot)


def test_skip_matches_manual_iteration_both_loaders(tmp_path, built):
    """skip(n) — the start_step→iterator resume contract for record
    streams — must land exactly where n next() calls land, natively and in
    the fallback, and degrade to StopIteration past the end."""
    files, _ = _write_files(tmp_path)

    def after_skip(cls, n):
        loader = cls(files, SPEC, batch_size=8, shuffle_records=0)
        return loader.skip(n).__next__()["idx"].tolist()

    def after_iter(cls, n):
        loader = cls(files, SPEC, batch_size=8, shuffle_records=0)
        for _ in range(n):
            next(loader)
        return next(loader)["idx"].tolist()

    for cls in (RecordLoader, PyRecordLoader):
        assert after_skip(cls, 3) == after_iter(cls, 3)
        with pytest.raises(StopIteration):
            next(cls(files, SPEC, batch_size=8).skip(10_000))


def test_python_fallback_rejects_spec_mismatch(tmp_path, built):
    files, _ = _write_files(tmp_path, per_file=(8,))
    wrong = RecordSpec.of(image=("float32", (2, 2)), label=("int32", ()))
    with pytest.raises(OSError, match="bad header"):
        next(PyRecordLoader(files, wrong, batch_size=2))
    with pytest.raises(OSError, match="bad header"):
        loader = RecordLoader(files, wrong, batch_size=2)
        next(loader)


def test_python_fallback_equivalence(tmp_path, built):
    """The fallback must agree with the native loader wherever behavior is
    specified: unshuffled order, sharding, remainder handling."""
    files, total = _write_files(tmp_path)

    def collect(cls):
        out = []
        loader = cls(
            files, SPEC, batch_size=8, shuffle_records=0,
            drop_remainder=False, shard_index=1, shard_count=2,
        )
        for b in loader:
            out.append(b["idx"].tolist())
        return out

    assert collect(RecordLoader) == collect(PyRecordLoader)


@pytest.mark.slow
def test_native_loader_throughput_sanity(tmp_path, built):
    """The native path must stream a meaningful data rate — this is the
    component whose job is not starving the chip."""
    import time

    n = 20_000
    spec = RecordSpec.of(x=("float32", (64,)), idx=("int64", ()))
    write_records_py(
        tmp_path / "big.kftr", spec,
        {"x": np.random.randn(n, 64).astype(np.float32),
         "idx": np.arange(n, dtype=np.int64)},
    )
    t0 = time.perf_counter()
    count = 0
    with RecordLoader(
        [tmp_path / "big.kftr"], spec, batch_size=256,
        shuffle_records=4096, seed=1, epochs=5,
    ) as loader:
        for b in loader:
            count += len(b["x"])
    dt = time.perf_counter() - t0
    rate = count * spec.record_bytes / dt / 1e6
    assert count == (5 * n // 256) * 256
    assert rate > 50, f"native loader only {rate:.1f} MB/s"
