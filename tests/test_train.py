"""train/: metrics writer, checkpointing, SPMD trainer on the 8-dev CPU mesh."""

import io

import jax
import numpy as np
import optax
import pytest

from kubeflow_tpu.core.mesh import MeshSpec
from kubeflow_tpu.data.synthetic import (
    ClassPrototypeDataset,
    TokenLMDataset,
    local_shard_iterator,
)
from kubeflow_tpu.models.mnist_cnn import MnistCNN, make_init_fn, make_loss_fn
from kubeflow_tpu.train.checkpoint import CheckpointConfig, Checkpointer
from kubeflow_tpu.train.loop import TrainConfig, Trainer
from kubeflow_tpu.train.metrics import MetricWriter, parse_stdout_metrics


def _mnist_trainer(tmp_path=None, steps=8, **cfg_kw):
    model = MnistCNN()
    return Trainer(
        init_params=make_init_fn(model),
        loss_fn=make_loss_fn(model),
        optimizer=optax.adam(3e-3),
        config=TrainConfig(
            mesh=MeshSpec.data_parallel(8),
            global_batch=32,
            steps=steps,
            log_every=2,
            **cfg_kw,
        ),
    )


def test_metric_writer_roundtrip(tmp_path):
    out = io.StringIO()
    with MetricWriter(tmp_path / "m", stdout=out) as w:
        w.write(1, {"loss": 2.5, "accuracy": 0.5})
        w.write(2, {"loss": 1.25, "accuracy": 0.75})
    text = out.getvalue()
    assert "step=1 loss=2.5 accuracy=0.5" in text
    parsed = parse_stdout_metrics(text)
    assert parsed[1]["loss"] == 1.25
    assert (tmp_path / "m" / "metrics.jsonl").exists()


def test_metric_writer_non_rank0_silent(tmp_path):
    out = io.StringIO()
    w = MetricWriter(tmp_path / "m2", is_writer=False, stdout=out)
    w.write(1, {"loss": 1.0})
    assert out.getvalue() == ""
    assert not (tmp_path / "m2" / "metrics.jsonl").exists()


def test_synthetic_datasets_deterministic():
    ds = ClassPrototypeDataset()
    x1, y1 = ds.batch(16, step=3, offset=1)
    x2, y2 = ds.batch(16, step=3, offset=1)
    np.testing.assert_array_equal(x1, x2)
    x3, _ = ds.batch(16, step=4, offset=1)
    assert not np.array_equal(x1, x3)

    lm = TokenLMDataset(vocab_size=64, seq_len=16)
    b = lm.batch(4, step=0)
    assert b["inputs"].shape == (4, 16)
    # autoregressive consistency: targets are inputs shifted by one
    np.testing.assert_array_equal(b["inputs"][:, 1:], b["targets"][:, :-1])


def test_local_shard_iterator_partitions():
    ds = ClassPrototypeDataset()
    it0 = local_shard_iterator(ds, 16, process_index=0, process_count=2)
    it1 = local_shard_iterator(ds, 16, process_index=1, process_count=2)
    x0, _ = next(it0)
    x1, _ = next(it1)
    assert x0.shape[0] == 8 and x1.shape[0] == 8
    assert not np.array_equal(x0, x1)  # different shards
    with pytest.raises(ValueError):
        next(local_shard_iterator(ds, 15, process_index=0, process_count=2))


def test_trainer_dp_loss_decreases(devices8):
    trainer = _mnist_trainer(steps=10)
    data = local_shard_iterator(ClassPrototypeDataset(), 32)
    state, history = trainer.fit(data)
    assert int(state.step) == 10
    assert history[-1]["loss"] < history[0]["loss"]
    # state is replicated over the whole mesh (pure DP)
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    assert leaf.sharding.is_fully_replicated


def test_trainer_checkpoint_resume(tmp_path, devices8):
    ckpt = CheckpointConfig(
        directory=str(tmp_path / "ckpt"), save_every_steps=2, async_save=False
    )
    t1 = _mnist_trainer(steps=4, checkpoint=ckpt)
    data = local_shard_iterator(ClassPrototypeDataset(), 32)
    state1, _ = t1.fit(data)
    assert int(state1.step) == 4

    # Second trainer with a longer horizon resumes from step 4, not 0.
    t2 = _mnist_trainer(steps=6, checkpoint=ckpt)
    state2, history2 = t2.fit(
        local_shard_iterator(ClassPrototypeDataset(), 32, start_step=4)
    )
    assert int(state2.step) == 6
    assert all(h["step"] > 4 for h in history2)
    # resumed params really came from the checkpoint: one more fit with
    # resume disabled starts from scratch at step 0..6 and differs
    p1 = jax.tree_util.tree_leaves(state1.params)[0]
    p2 = jax.tree_util.tree_leaves(state2.params)[0]
    assert not np.array_equal(np.asarray(p1), np.asarray(p2))


def test_checkpointer_restore_to_different_mesh(tmp_path, devices8):
    """Elastic-restart core property: save on mesh A, restore on mesh B."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubeflow_tpu.core.mesh import Axis, build_mesh

    cfg = CheckpointConfig(directory=str(tmp_path / "c"), async_save=False)
    mesh8 = build_mesh(MeshSpec.fsdp_parallel(8))
    x = jax.device_put(
        np.arange(64, dtype=np.float32).reshape(8, 8),
        NamedSharding(mesh8, P(Axis.FSDP)),
    )
    with Checkpointer(cfg) as c:
        c.save(1, {"x": x}, force=True)

    mesh4 = build_mesh(MeshSpec.fsdp_parallel(4), devices=jax.devices()[:4])
    target = jax.ShapeDtypeStruct(
        (8, 8), np.float32, sharding=NamedSharding(mesh4, P(Axis.FSDP))
    )
    with Checkpointer(cfg) as c2:
        restored = c2.restore({"x": target})
    assert restored["x"].sharding.mesh.devices.size == 4
    np.testing.assert_array_equal(
        np.asarray(restored["x"]), np.arange(64, dtype=np.float32).reshape(8, 8)
    )
    with pytest.raises(FileNotFoundError):
        Checkpointer(
            CheckpointConfig(directory=str(tmp_path / "empty"))
        ).restore({"x": target})
