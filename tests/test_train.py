"""train/: metrics writer, checkpointing, SPMD trainer on the 8-dev CPU mesh."""

import io

import jax
import numpy as np
import optax
import pytest

from kubeflow_tpu.core.mesh import MeshSpec
from kubeflow_tpu.data.synthetic import (
    ClassPrototypeDataset,
    TokenLMDataset,
    local_shard_iterator,
)
from kubeflow_tpu.models.mnist_cnn import MnistCNN, make_init_fn, make_loss_fn
from kubeflow_tpu.train.checkpoint import CheckpointConfig, Checkpointer
from kubeflow_tpu.train.loop import TrainConfig, Trainer
from kubeflow_tpu.train.metrics import MetricWriter, parse_stdout_metrics


def _mnist_trainer(tmp_path=None, steps=8, **cfg_kw):
    model = MnistCNN()
    return Trainer(
        init_params=make_init_fn(model),
        loss_fn=make_loss_fn(model),
        optimizer=optax.adam(3e-3),
        config=TrainConfig(
            mesh=MeshSpec.data_parallel(8),
            global_batch=32,
            steps=steps,
            log_every=2,
            **cfg_kw,
        ),
    )


def test_metric_writer_roundtrip(tmp_path):
    out = io.StringIO()
    with MetricWriter(tmp_path / "m", stdout=out) as w:
        w.write(1, {"loss": 2.5, "accuracy": 0.5})
        w.write(2, {"loss": 1.25, "accuracy": 0.75})
    text = out.getvalue()
    assert "step=1 loss=2.5 accuracy=0.5" in text
    parsed = parse_stdout_metrics(text)
    assert parsed[1]["loss"] == 1.25
    assert (tmp_path / "m" / "metrics.jsonl").exists()


def test_metric_writer_non_rank0_silent(tmp_path):
    out = io.StringIO()
    w = MetricWriter(tmp_path / "m2", is_writer=False, stdout=out)
    w.write(1, {"loss": 1.0})
    assert out.getvalue() == ""
    assert not (tmp_path / "m2" / "metrics.jsonl").exists()


def test_synthetic_datasets_deterministic():
    ds = ClassPrototypeDataset()
    x1, y1 = ds.batch(16, step=3, offset=1)
    x2, y2 = ds.batch(16, step=3, offset=1)
    np.testing.assert_array_equal(x1, x2)
    x3, _ = ds.batch(16, step=4, offset=1)
    assert not np.array_equal(x1, x3)

    lm = TokenLMDataset(vocab_size=64, seq_len=16)
    b = lm.batch(4, step=0)
    assert b["inputs"].shape == (4, 16)
    # autoregressive consistency: targets are inputs shifted by one
    np.testing.assert_array_equal(b["inputs"][:, 1:], b["targets"][:, :-1])


def test_local_shard_iterator_partitions():
    ds = ClassPrototypeDataset()
    it0 = local_shard_iterator(ds, 16, process_index=0, process_count=2)
    it1 = local_shard_iterator(ds, 16, process_index=1, process_count=2)
    x0, _ = next(it0)
    x1, _ = next(it1)
    assert x0.shape[0] == 8 and x1.shape[0] == 8
    assert not np.array_equal(x0, x1)  # different shards
    with pytest.raises(ValueError):
        next(local_shard_iterator(ds, 15, process_index=0, process_count=2))


def test_trainer_dp_loss_decreases(devices8):
    trainer = _mnist_trainer(steps=10)
    data = local_shard_iterator(ClassPrototypeDataset(), 32)
    state, history = trainer.fit(data)
    assert int(state.step) == 10
    assert history[-1]["loss"] < history[0]["loss"]
    # state is replicated over the whole mesh (pure DP)
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    assert leaf.sharding.is_fully_replicated


def test_trainer_checkpoint_resume(tmp_path, devices8):
    ckpt = CheckpointConfig(
        directory=str(tmp_path / "ckpt"), save_every_steps=2, async_save=False
    )
    t1 = _mnist_trainer(steps=4, checkpoint=ckpt)
    data = local_shard_iterator(ClassPrototypeDataset(), 32)
    state1, _ = t1.fit(data)
    assert int(state1.step) == 4

    # Second trainer with a longer horizon resumes from step 4, not 0.
    t2 = _mnist_trainer(steps=6, checkpoint=ckpt)
    state2, history2 = t2.fit(
        local_shard_iterator(ClassPrototypeDataset(), 32, start_step=4)
    )
    assert int(state2.step) == 6
    assert all(h["step"] > 4 for h in history2)
    # resumed params really came from the checkpoint: one more fit with
    # resume disabled starts from scratch at step 0..6 and differs
    p1 = jax.tree_util.tree_leaves(state1.params)[0]
    p2 = jax.tree_util.tree_leaves(state2.params)[0]
    assert not np.array_equal(np.asarray(p1), np.asarray(p2))


def test_local_batch_size_divisibility_raises_not_truncates():
    t = _mnist_trainer()
    assert t.local_batch_size(process_count=2) == 16
    with pytest.raises(ValueError, match="not divisible"):
        t.local_batch_size(process_count=3)
    # config-level: a global batch that doesn't split into microbatches
    with pytest.raises(ValueError, match="grad_accum_steps"):
        _mnist_trainer(grad_accum_steps=5)


def test_grad_accum_numerics_parity(devices8):
    """accum=4 must match accum=1 losses to fp32 tolerance at the same
    effective global batch (one optimizer update per step either way)."""
    histories = []
    for accum in (1, 4):
        trainer = _mnist_trainer(steps=6, grad_accum_steps=accum)
        data = local_shard_iterator(ClassPrototypeDataset(), 32)
        state, history = trainer.fit(data)
        assert int(state.step) == 6
        histories.append([h["loss"] for h in history])
    np.testing.assert_allclose(histories[0], histories[1], rtol=2e-5, atol=1e-6)


def test_prefetch_on_off_identical_per_step_metrics(devices8):
    """The overlap layer must not change the math: same batches, same
    order, same per-step losses whether placement is inline or threaded."""
    runs = []
    for depth in (0, 3):
        trainer = _mnist_trainer(steps=8, prefetch_depth=depth)
        _, history = trainer.fit(local_shard_iterator(ClassPrototypeDataset(), 32))
        runs.append(history)
    for h0, h3 in zip(runs[0], runs[1]):
        assert h0["step"] == h3["step"]
        np.testing.assert_array_equal(h0["loss"], h3["loss"])
        np.testing.assert_array_equal(h0["accuracy"], h3["accuracy"])


def test_overlap_gauges_in_writer_output_and_prom(devices8):
    out = io.StringIO()
    trainer = _mnist_trainer(steps=4)
    with MetricWriter(None, stdout=out) as w:
        trainer.fit(local_shard_iterator(ClassPrototypeDataset(), 32), writer=w)
    parsed = parse_stdout_metrics(out.getvalue())
    assert parsed, out.getvalue()
    first, last = parsed[0], parsed[-1]
    # the split instrumentation rides every logged window...
    for key in ("data_stall_ms", "h2d_ms", "device_step_ms", "steps_per_sec"):
        assert key in last, (key, last)
    # ...and compile time is its own metric, reported exactly once
    assert "compile_ms" in first and first["compile_ms"] > 0
    assert "compile_ms" not in last
    # mirrored onto the process-wide prom registry (the shared /metrics)
    from kubeflow_tpu.obs.prom import REGISTRY

    text = REGISTRY.expose()
    for name in (
        "kubeflow_tpu_train_data_stall_ms",
        "kubeflow_tpu_train_device_step_ms",
        "kubeflow_tpu_train_compile_ms",
    ):
        assert name in text


def test_fit_joins_overlap_threads(devices8):
    from kubeflow_tpu.train.prefetch import live_kft_threads

    trainer = _mnist_trainer(steps=4, prefetch_depth=2)
    trainer.fit(local_shard_iterator(ClassPrototypeDataset(), 32))
    assert live_kft_threads() == []


def test_resume_with_prefetch_neither_loses_nor_replays_batches(
    tmp_path, devices8
):
    """Batches buffered in the prefetcher when the first run exits must be
    regenerated by the resume factory, not lost or double-trained: a
    checkpointed 4+4 run must land bit-for-bit where an unbroken 8-step run
    lands (state includes the optimizer, so any drift means the stream
    skipped or replayed)."""
    requested_starts: list[int] = []

    def factory(start_step):
        requested_starts.append(start_step)
        return local_shard_iterator(
            ClassPrototypeDataset(), 32, start_step=start_step
        )

    ckpt = CheckpointConfig(
        directory=str(tmp_path / "ckpt"), save_every_steps=2, async_save=False
    )
    t1 = _mnist_trainer(steps=4, checkpoint=ckpt, prefetch_depth=3)
    t1.fit(factory)
    t2 = _mnist_trainer(steps=8, checkpoint=ckpt, prefetch_depth=3)
    resumed, _ = t2.fit(factory)
    assert requested_starts == [0, 4]

    t3 = _mnist_trainer(steps=8, prefetch_depth=3)
    unbroken, _ = t3.fit(factory)
    for a, b in zip(
        jax.tree_util.tree_leaves(resumed.params),
        jax.tree_util.tree_leaves(unbroken.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


def test_checkpointer_restore_to_different_mesh(tmp_path, devices8):
    """Elastic-restart core property: save on mesh A, restore on mesh B."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubeflow_tpu.core.mesh import Axis, build_mesh

    cfg = CheckpointConfig(directory=str(tmp_path / "c"), async_save=False)
    mesh8 = build_mesh(MeshSpec.fsdp_parallel(8))
    x = jax.device_put(
        np.arange(64, dtype=np.float32).reshape(8, 8),
        NamedSharding(mesh8, P(Axis.FSDP)),
    )
    with Checkpointer(cfg) as c:
        c.save(1, {"x": x}, force=True)

    mesh4 = build_mesh(MeshSpec.fsdp_parallel(4), devices=jax.devices()[:4])
    target = jax.ShapeDtypeStruct(
        (8, 8), np.float32, sharding=NamedSharding(mesh4, P(Axis.FSDP))
    )
    with Checkpointer(cfg) as c2:
        restored = c2.restore({"x": target})
    assert restored["x"].sharding.mesh.devices.size == 4
    np.testing.assert_array_equal(
        np.asarray(restored["x"]), np.arange(64, dtype=np.float32).reshape(8, 8)
    )
    with pytest.raises(FileNotFoundError):
        Checkpointer(
            CheckpointConfig(directory=str(tmp_path / "empty"))
        ).restore({"x": target})
