"""Manifest overlay/layering plane (SURVEY.md §2.5 "Manifests" row, §1 L8):
kustomize-equivalent base+overlay builds over this framework's manifests."""

import textwrap

import pytest
import yaml

from kubeflow_tpu.platform import manifests as km


def _write(path, text):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(text))


@pytest.fixture()
def tree(tmp_path):
    """base (JAXJob + ISVC) + dev overlay + prod overlay-of-overlay."""
    _write(tmp_path / "base" / "job.yaml", """
        kind: JAXJob
        metadata:
          name: train
        spec:
          jaxReplicaSpecs:
            Worker:
              replicas: 2
              template:
                spec:
                  containers:
                    - name: jax
                      command: ["python", "-m", "kubeflow_tpu.examples.mnist"]
    """)
    _write(tmp_path / "base" / "isvc.yaml", """
        kind: InferenceService
        metadata:
          name: bert
        spec:
          predictor:
            model:
              modelFormat:
                name: bert-tiny
    """)
    _write(tmp_path / "base" / "kustomization.yaml", """
        resources:
          - job.yaml
          - isvc.yaml
    """)
    _write(tmp_path / "dev" / "kustomization.yaml", """
        resources:
          - ../base
        namePrefix: dev-
        commonLabels:
          env: dev
        patchesStrategicMerge:
          - patch_job.yaml
    """)
    _write(tmp_path / "dev" / "patch_job.yaml", """
        kind: JAXJob
        metadata:
          name: train
        spec:
          jaxReplicaSpecs:
            Worker:
              replicas: 4
    """)
    _write(tmp_path / "prod" / "kustomization.yaml", """
        resources:
          - ../dev
        namespace: prod
        nameSuffix: -v2
        patches:
          - target:
              kind: JAXJob
            patch: |
              spec:
                runPolicy:
                  backoffLimit: 3
        configMapGenerator:
          - name: train-config
            literals:
              - LR=0.001
              - STEPS=100
    """)
    return tmp_path


def test_base_build(tree):
    objs = km.build(str(tree / "base"))
    assert [m["kind"] for m in objs] == ["JAXJob", "InferenceService"]
    assert objs[0]["spec"]["jaxReplicaSpecs"]["Worker"]["replicas"] == 2


def test_overlay_patches_and_transformers(tree):
    objs = km.build(str(tree / "dev"))
    job = next(m for m in objs if m["kind"] == "JAXJob")
    # strategic merge changed replicas but kept the container command
    assert job["spec"]["jaxReplicaSpecs"]["Worker"]["replicas"] == 4
    containers = job["spec"]["jaxReplicaSpecs"]["Worker"]["template"]["spec"][
        "containers"
    ]
    assert containers[0]["command"][0] == "python"
    # transformers applied to every resource
    for m in objs:
        assert m["metadata"]["name"].startswith("dev-")
        assert m["metadata"]["labels"]["env"] == "dev"


def test_overlay_of_overlay(tree):
    objs = km.build(str(tree / "prod"))
    job = next(m for m in objs if m["kind"] == "JAXJob")
    assert job["metadata"]["name"] == "dev-train-v2"
    assert job["metadata"]["namespace"] == "prod"
    assert job["spec"]["jaxReplicaSpecs"]["Worker"]["replicas"] == 4
    assert job["spec"]["runPolicy"]["backoffLimit"] == 3
    cm = next(m for m in objs if m["kind"] == "ConfigMap")
    assert cm["data"] == {"LR": "0.001", "STEPS": "100"}
    # generators belong to the level that declares them: prod's transformers
    # apply (suffix), dev's do not (no prefix) — kustomize semantics
    assert cm["metadata"]["name"] == "train-config-v2"


def test_strategic_merge_semantics():
    base = {
        "containers": [
            {"name": "a", "image": "x", "env": [{"name": "K", "value": "1"}]},
            {"name": "b", "image": "y"},
        ],
        "drop_me": 1,
        "keep": {"deep": True},
    }
    patch = {
        "containers": [{"name": "a", "image": "x2"}],
        "drop_me": None,
        "keep": {"extra": 2},
    }
    out = km.strategic_merge(base, patch)
    by_name = {c["name"]: c for c in out["containers"]}
    assert by_name["a"]["image"] == "x2"
    assert by_name["a"]["env"] == [{"name": "K", "value": "1"}]  # merged, kept
    assert by_name["b"]["image"] == "y"  # untouched sibling survives
    assert "drop_me" not in out  # null deletes
    assert out["keep"] == {"deep": True, "extra": 2}


def test_unmatched_patch_is_an_error(tree):
    with pytest.raises(ValueError, match="target not found"):
        km.build(
            {
                "resources": [str(tree / "base")],
                "patchesStrategicMerge": [
                    {"kind": "JAXJob", "metadata": {"name": "ghost"}}
                ],
            }
        )


def test_build_then_parse_then_submit(tree, tmp_path):
    """The `kubectl apply -k` path: built manifests parse to typed specs
    and a JAXJob actually runs through the cluster."""
    import sys

    from kubeflow_tpu.orchestrator.cluster import LocalCluster
    from kubeflow_tpu.orchestrator.spec import JobSpec

    objs = km.build(str(tree / "dev"))
    specs = [km.parse(m) for m in objs]
    job = next(s for s in specs if isinstance(s, JobSpec))
    assert job.name == "dev-train"
    assert job.replicas["worker"].replicas == 4

    # shrink to something that actually finishes, then run it
    fast = km.build(
        {
            "resources": [str(tree / "base")],
            "patchesStrategicMerge": [
                {
                    "kind": "JAXJob",
                    "metadata": {"name": "train"},
                    "spec": {
                        "jaxReplicaSpecs": {
                            "Worker": {
                                "replicas": 1,
                                "template": {
                                    "spec": {
                                        "containers": [
                                            {
                                                "name": "jax",
                                                "command": [
                                                    sys.executable,
                                                    "-c",
                                                    "print('ok')",
                                                ],
                                            }
                                        ]
                                    }
                                },
                            }
                        }
                    },
                }
            ],
        }
    )
    spec = km.parse(next(m for m in fast if m["kind"] == "JAXJob"))
    with LocalCluster(base_dir=str(tmp_path / "c")) as cluster:
        uid = cluster.submit(spec)
        status = cluster.wait(uid, timeout=60)
    assert status.phase == "Succeeded"


def test_experiment_manifest_parses():
    exp = km.parse(
        {
            "kind": "Experiment",
            "metadata": {"name": "sweep"},
            "spec": yaml.safe_load(
                """
                parameters:
                  - name: lr
                    type: double
                    min: 0.0001
                    max: 0.1
                objective:
                  metric: loss
                  type: minimize
                algorithm:
                  name: random
                max_trial_count: 4
                parallel_trial_count: 2
                """
            ),
        }
    )
    assert exp.name == "sweep" and exp.parameters[0].name == "lr"


def test_example_overlay_tree_builds_and_parses():
    """The shipped examples/manifests tree is a working overlay stack."""
    import pathlib

    import kubeflow_tpu

    root = pathlib.Path(kubeflow_tpu.__file__).parent / "examples" / "manifests"
    objs = km.build(str(root / "overlays" / "dev"))
    kinds = sorted(m["kind"] for m in objs)
    assert kinds == ["InferenceService", "JAXJob"]
    for m in objs:
        assert m["metadata"]["name"].startswith("dev-")
        km.parse(m)  # typed parse must succeed for every shipped manifest
