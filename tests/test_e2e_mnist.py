"""MINIMUM END-TO-END SLICE (SURVEY.md §7 step 3, BASELINE config 1 analog).

JobSpec(mnist, workers=2) → gang launcher → 2 processes → jax.distributed
rendezvous → DP training over an 8-device (2 hosts x 4) gloo-backed mesh →
metrics on stdout → checkpoint → Succeeded condition. This is the kind-e2e
analog: real processes, real cross-process collectives, no cluster.
"""

import sys
from pathlib import Path

import pytest

from kubeflow_tpu.orchestrator import (
    JobSpec,
    LocalCluster,
    ReplicaSpec,
    TPURequest,
)
from kubeflow_tpu.orchestrator.envwire import WiringConfig
from kubeflow_tpu.orchestrator.resources import Fleet
from kubeflow_tpu.train.metrics import parse_stdout_metrics

REPO = str(Path(__file__).resolve().parent.parent)
PY = sys.executable


@pytest.mark.slow
def test_jaxjob_mnist_two_process_gang(tmp_path):
    cluster = LocalCluster(
        fleet=Fleet.homogeneous(2, "2x2"),
        wiring=WiringConfig(platform="cpu_sim", devices_per_worker=4),
        base_dir=str(tmp_path),
        resync_period=0.05,
    )
    with cluster:
        job = JobSpec(
            name="mnist-dp",
            replicas={
                "worker": ReplicaSpec(
                    replicas=2,
                    command=(
                        PY, "-m", "kubeflow_tpu.examples.mnist",
                        "--steps", "6", "--global-batch", "32",
                        "--log-every", "2", "--lr", "3e-3",
                        "--checkpoint-dir", str(tmp_path / "ckpt"),
                        "--checkpoint-every", "3",
                    ),
                    env={"PYTHONPATH": REPO},
                    tpu=TPURequest(chips=4),
                )
            },
        )
        uid = cluster.submit(job)
        status = cluster.wait(uid, timeout=600)
        log0 = cluster.logs(uid, "worker", 0)
        log1 = cluster.logs(uid, "worker", 1)
        assert status.phase == "Succeeded", f"rank0 log:\n{log0}\nrank1:\n{log1}"

        # world formed: every process saw 4 local / 8 global devices
        assert "4 local / 8 global" in log0 and "4 local / 8 global" in log1
        # rank-0 gating: metrics only on worker-0's stdout
        metrics = parse_stdout_metrics(log0)
        assert [m["step"] for m in metrics] == [2, 4, 6]
        assert metrics[-1]["loss"] < metrics[0]["loss"]
        assert parse_stdout_metrics(log1) == []
        assert "final_loss=" in log0
        # checkpoint written and readable
        assert any((tmp_path / "ckpt").iterdir())
