"""HF→JAX checkpoint conversion parity (SURVEY.md §2.2 HuggingFace runtime
row): the SAME weights must produce the SAME outputs, so reference users'
torch BERT checkpoints serve and fine-tune here unchanged."""

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from kubeflow_tpu.models.bert import BertEncoder  # noqa: E402
from kubeflow_tpu.models.convert import (  # noqa: E402
    bert_config_from_hf,
    hf_bert_state_to_params,
    load_bert_dir,
)

HF_CFG = dict(
    vocab_size=99,
    hidden_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    intermediate_size=128,
    max_position_embeddings=64,
    type_vocab_size=2,
    hidden_act="gelu",
    layer_norm_eps=1e-12,
)


@pytest.fixture(scope="module")
def hf_model():
    torch.manual_seed(0)
    cfg = transformers.BertConfig(**HF_CFG)
    model = transformers.BertModel(cfg, add_pooling_layer=True)
    model.eval()
    return model


def _inputs(batch=3, seq=16, seed=1):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, HF_CFG["vocab_size"], size=(batch, seq))
    types = rng.randint(0, 2, size=(batch, seq))
    return ids.astype(np.int32), types.astype(np.int32)


def test_config_mapping():
    cfg = bert_config_from_hf(HF_CFG)
    assert cfg.hidden_size == 64
    assert cfg.num_layers == 2
    assert cfg.num_heads == 4
    assert cfg.max_position == 64


def test_forward_parity_full_mask(hf_model):
    ids, types = _inputs()
    with torch.no_grad():
        out = hf_model(
            input_ids=torch.from_numpy(ids.astype(np.int64)),
            token_type_ids=torch.from_numpy(types.astype(np.int64)),
        )
    cfg = bert_config_from_hf(HF_CFG, attn_impl="reference")
    params = hf_bert_state_to_params(hf_model.state_dict(), cfg)
    seq_out, pooled = BertEncoder(cfg).apply(
        {"params": params},
        jnp.asarray(ids),
        token_type_ids=jnp.asarray(types),
    )
    np.testing.assert_allclose(
        np.asarray(seq_out), out.last_hidden_state.numpy(), atol=2e-5, rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(pooled), out.pooler_output.numpy(), atol=2e-5, rtol=1e-4
    )


def test_forward_parity_with_padding(hf_model):
    ids, types = _inputs(batch=2, seq=12)
    mask = np.ones_like(ids)
    mask[:, 8:] = 0  # last 4 positions are padding
    with torch.no_grad():
        out = hf_model(
            input_ids=torch.from_numpy(ids.astype(np.int64)),
            attention_mask=torch.from_numpy(mask.astype(np.int64)),
            token_type_ids=torch.from_numpy(types.astype(np.int64)),
        )
    cfg = bert_config_from_hf(HF_CFG, attn_impl="reference")
    params = hf_bert_state_to_params(hf_model.state_dict(), cfg)
    seq_out, pooled = BertEncoder(cfg).apply(
        {"params": params},
        jnp.asarray(ids),
        attention_mask=jnp.asarray(mask),
        token_type_ids=jnp.asarray(types),
    )
    # only valid (unpadded) positions are defined outputs
    ours = np.asarray(seq_out)[:, :8]
    theirs = out.last_hidden_state.numpy()[:, :8]
    np.testing.assert_allclose(ours, theirs, atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(pooled), out.pooler_output.numpy(), atol=2e-5, rtol=1e-4
    )


def test_prefixed_state_dict_from_downstream_model():
    torch.manual_seed(1)
    cfg_t = transformers.BertConfig(**HF_CFG)
    clf = transformers.BertForSequenceClassification(cfg_t)
    clf.eval()
    cfg = bert_config_from_hf(HF_CFG, attn_impl="reference")
    params = hf_bert_state_to_params(clf.state_dict(), cfg)
    assert "layers_1" in params and "pooler" in params

    ids, types = _inputs(batch=2, seq=8, seed=3)
    with torch.no_grad():
        hf_seq = clf.bert(
            input_ids=torch.from_numpy(ids.astype(np.int64)),
            token_type_ids=torch.from_numpy(types.astype(np.int64)),
        ).last_hidden_state.numpy()
    seq_out, _ = BertEncoder(cfg).apply(
        {"params": params}, jnp.asarray(ids), token_type_ids=jnp.asarray(types)
    )
    np.testing.assert_allclose(np.asarray(seq_out), hf_seq, atol=2e-5, rtol=1e-4)


def test_load_bert_dir_roundtrip(tmp_path, hf_model):
    (tmp_path / "config.json").write_text(json.dumps(HF_CFG))
    torch.save(hf_model.state_dict(), tmp_path / "pytorch_model.bin")
    cfg, params = load_bert_dir(tmp_path, attn_impl="reference")
    assert cfg.num_layers == 2
    ids, types = _inputs(batch=1, seq=8)
    seq_out, _ = BertEncoder(cfg).apply(
        {"params": params}, jnp.asarray(ids), token_type_ids=jnp.asarray(types)
    )
    assert np.isfinite(np.asarray(seq_out)).all()
    with pytest.raises(FileNotFoundError, match="config.json"):
        load_bert_dir(tmp_path / "nope")
