"""Platform plane (SURVEY.md §2.5): admission webhooks, PodDefaults,
Profile quotas, notebook culling, tensorboard controller, dashboard API."""

import json
import sys
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.orchestrator import (
    JobSpec,
    LocalCluster,
    ReplicaSpec,
    SchedulingPolicy,
    RunPolicy,
    TPURequest,
)
from kubeflow_tpu.orchestrator.resources import Fleet
from kubeflow_tpu.orchestrator.webhooks import AdmissionChain, AdmissionError
from kubeflow_tpu.platform import (
    DashboardServer,
    NotebookController,
    NotebookSpec,
    PodDefault,
    Profile,
    ProfileController,
    ResourceQuota,
    TensorboardController,
    TensorboardSpec,
)

PY = sys.executable
SLEEP = (PY, "-c", "import time; time.sleep(60)")
QUICK = (PY, "-c", "pass")


@pytest.fixture()
def cluster(tmp_path):
    c = LocalCluster(
        fleet=Fleet.homogeneous(4, "2x2"),
        base_dir=str(tmp_path),
        resync_period=0.05,
    )
    with c:
        yield c


def _job(name, command=SLEEP, ns="default", chips=0, replicas=1, **labels):
    return JobSpec(
        name=name,
        namespace=ns,
        labels=dict(labels),
        replicas={
            "worker": ReplicaSpec(
                replicas=replicas, command=command, tpu=TPURequest(chips=chips)
            )
        },
    )


# -- admission ------------------------------------------------------------ #


def test_admission_builtin_rejects_bad_min_available(cluster):
    bad = JobSpec(
        name="bad",
        replicas={"worker": ReplicaSpec(replicas=2, command=QUICK)},
        run_policy=RunPolicy(scheduling=SchedulingPolicy(min_available=5)),
    )
    with pytest.raises(AdmissionError, match="minAvailable"):
        cluster.submit(bad)


def test_admission_mutator_and_validator_order():
    chain = AdmissionChain()
    seen = []
    chain.add_mutator(lambda s: (seen.append("m1"), s)[1])

    def reject(spec):
        seen.append("v1")
        raise AdmissionError("nope")

    chain.add_validator(reject)
    with pytest.raises(AdmissionError, match="nope"):
        chain.admit(_job("x"))
    assert seen == ["m1", "v1"]  # mutators before validators


def test_poddefault_injects_env_without_overriding():
    pd = PodDefault(
        name="tracking",
        selector={"team": "research"},
        env={"WANDB_MODE": "offline", "KEEP": "default"},
        labels={"injected": "yes"},
    )
    job = _job("a", team="research")
    job.replicas["worker"] = ReplicaSpec(
        replicas=1, command=QUICK, env={"KEEP": "mine"}
    )
    out = pd(job)
    assert out.replicas["worker"].env == {"WANDB_MODE": "offline", "KEEP": "mine"}
    assert out.labels["injected"] == "yes"

    unmatched = pd(_job("b", team="serving"))
    assert "WANDB_MODE" not in unmatched.replicas["worker"].env

    # purity: the caller's object is untouched (retried submits must not
    # see silently merged defaults)
    assert job.replicas["worker"].env == {"KEEP": "mine"}
    assert "injected" not in job.labels


def test_logserver_scalars_robustness(tmp_path):
    from kubeflow_tpu.platform.logserver import find_runs, read_scalars

    run = tmp_path / "r"
    run.mkdir()
    (run / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "time": 1.0, "loss": 3.0}) + "\n"
        + json.dumps({"loss": 9.9, "time": 2.0}) + "\n"  # no step: skipped
        + "{not json\n"
        + json.dumps({"step": 2, "time": 3.0, "loss": 2.0}) + "\n"
    )
    assert read_scalars(run) == {"loss": [[1.0, 1.0, 3.0], [2.0, 3.0, 2.0]]}
    assert find_runs(tmp_path) == ["r"]


# -- profiles / quota ----------------------------------------------------- #


def test_profile_quota_enforced_at_admission(cluster):
    profiles = ProfileController(cluster)
    profiles.create(
        Profile(
            name="team-a",
            owner="ada",
            quota=ResourceQuota(max_chips=8, max_jobs=2),
        )
    )
    profiles.install()

    uid1 = cluster.submit(_job("j1", ns="team-a", chips=4))
    assert uid1
    with pytest.raises(AdmissionError, match="chips"):
        cluster.submit(_job("j2", ns="team-a", chips=8))
    uid2 = cluster.submit(_job("j3", ns="team-a", chips=2))
    with pytest.raises(AdmissionError, match="jobs already live"):
        cluster.submit(_job("j4", ns="team-a", chips=1))
    # other namespaces are unmanaged (non-strict)
    assert cluster.submit(_job("free", ns="team-b", chips=4))
    usage = profiles.usage("team-a")
    assert usage == {"chips": 6, "jobs": 2}

    # finishing a job releases quota
    cluster.delete(uid1)
    deadline = time.time() + 10
    while time.time() < deadline and cluster.get(uid1) is not None:
        time.sleep(0.05)
    assert cluster.submit(_job("j5", ns="team-a", chips=4))


def test_strict_profile_requires_namespace(cluster):
    profiles = ProfileController(cluster, strict=True)
    profiles.install()
    with pytest.raises(AdmissionError, match="no profile"):
        cluster.submit(_job("x", ns="nowhere"))


def test_profile_access_rules():
    p = Profile(name="t", owner="ada", contributors=["grace"])
    assert p.can_act("ada") and p.can_act("grace")
    assert not p.can_act("mallory")


# -- notebooks ------------------------------------------------------------ #


def test_notebook_lifecycle_and_culling(cluster):
    nb = NotebookController(cluster)
    nb.create(
        NotebookSpec(
            name="ws", command=SLEEP, culling_idle_seconds=0.5
        )
    )
    deadline = time.time() + 30
    while time.time() < deadline and nb.get("ws").phase != "Running":
        time.sleep(0.05)
    assert nb.get("ws").phase == "Running"

    # touches hold the culler off
    nb.touch("ws")
    nb.reconcile()
    assert nb.get("ws").phase == "Running"

    # idle past the deadline → culled, job deleted
    time.sleep(0.7)
    nb.reconcile()
    st = nb.get("ws")
    assert st.phase == "Culled" and st.job_uid is None

    # wake restarts it
    st = nb.wake("ws")
    deadline = time.time() + 30
    while time.time() < deadline and nb.get("ws").phase != "Running":
        time.sleep(0.05)
    assert nb.get("ws").phase == "Running"
    nb.delete("ws")


# -- tensorboards --------------------------------------------------------- #


def test_tensorboard_controller_serves_scalars(cluster, tmp_path):
    # a run directory in the MetricWriter layout
    run = tmp_path / "logs" / "run1"
    run.mkdir(parents=True)
    (run / "metrics.jsonl").write_text(
        json.dumps({"step": 1, "time": 1.0, "loss": 2.0}) + "\n"
        + json.dumps({"step": 2, "time": 2.0, "loss": 1.5}) + "\n"
    )

    tb = TensorboardController(cluster)
    status = tb.create(
        TensorboardSpec(name="tb1", logdir=str(tmp_path / "logs"))
    )
    assert status.port > 0

    # the server must actually answer HTTP — phase alone can hide a crash
    # loop behind restart-Always (the bug /verify caught with real
    # tensorboard.main, which cannot start in this image)
    deadline = time.time() + 60
    scalars = None
    while time.time() < deadline:
        st = tb.get("tb1")
        assert st.phase != "CrashLooping", cluster.logs(
            st.job_uid, "server", 0
        )
        try:
            scalars = json.loads(
                urllib.request.urlopen(
                    status.url + "/api/scalars?run=run1", timeout=2
                ).read()
            )
            break
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.2)
    assert scalars == {"loss": [[1.0, 1.0, 2.0], [2.0, 2.0, 1.5]]}
    runs = json.loads(
        urllib.request.urlopen(status.url + "/api/runs", timeout=2).read()
    )
    assert runs == ["run1"]

    with pytest.raises(ValueError, match="already exists"):
        tb.create(TensorboardSpec(name="tb1", logdir=str(tmp_path)))
    tb.delete("tb1")


def test_tensorboard_surfaces_crash_loop(cluster, tmp_path):
    tb = TensorboardController(cluster)
    tb.create(
        TensorboardSpec(
            name="broken", logdir=str(tmp_path),
            command=(PY, "-c", "raise SystemExit(1)"),
        )
    )
    deadline = time.time() + 30
    while time.time() < deadline and tb.get("broken").phase != "CrashLooping":
        time.sleep(0.05)
    assert tb.get("broken").phase == "CrashLooping"
    tb.delete("broken")


# -- dashboard ------------------------------------------------------------ #


def test_dashboard_aggregates_all_planes(cluster, tmp_path):
    profiles = ProfileController(cluster)
    profiles.create(
        Profile(name="team-a", owner="ada", quota=ResourceQuota(max_chips=8))
    )
    profiles.install()
    nb = NotebookController(cluster)
    tb = TensorboardController(cluster)

    cluster.submit(_job("j1", ns="team-a", chips=2))
    nb.create(NotebookSpec(name="ws", command=SLEEP))
    tb.create(TensorboardSpec(name="tb1", logdir=str(tmp_path)))

    with DashboardServer(
        cluster, profiles=profiles, notebooks=nb, tensorboards=tb
    ) as dash:
        summary = json.loads(
            urllib.request.urlopen(dash.url + "/api/summary").read()
        )
        assert summary["jobs"]["total"] == 3  # j1 + notebook + tensorboard
        assert summary["profiles"] == 1
        assert summary["notebooks"] == 1
        assert summary["tensorboards"] == 1
        assert summary["fleet"]["total_chips"] == 16

        jobs = json.loads(urllib.request.urlopen(dash.url + "/api/jobs").read())
        names = {j["name"] for j in jobs}
        assert names == {"j1", "notebook-ws", "tensorboard-tb1"}

        profs = json.loads(
            urllib.request.urlopen(dash.url + "/api/profiles").read()
        )
        assert profs[0]["usage"]["chips"] == 2

        nbs = json.loads(
            urllib.request.urlopen(dash.url + "/api/notebooks").read()
        )
        assert nbs[0]["name"] == "ws"


def test_dashboard_ui_and_crud(cluster, tmp_path):
    """§2.5 CRUD web-app analog: the dashboard serves an HTML UI and
    writable endpoints — submit/delete jobs, notebooks, tensorboards over
    HTTP, read logs back."""
    import urllib.error

    nb = NotebookController(cluster)
    tb = TensorboardController(cluster)
    with DashboardServer(cluster, notebooks=nb, tensorboards=tb) as dash:
        def call(method, path, body=None):
            req = urllib.request.Request(
                dash.url + path,
                method=method,
                data=json.dumps(body).encode() if body is not None else None,
                headers={"content-type": "application/json"},
            )
            with urllib.request.urlopen(req) as r:
                raw = r.read().decode()
                return json.loads(raw) if raw.startswith(("{", "[")) else raw

        # HTML SPA served at /
        html = call("GET", "/")
        assert "<!doctype html>" in html and "/api/summary" in html

        # job CRUD through a CRD manifest
        out = call("POST", "/api/jobs", {
            "kind": "JAXJob",
            "metadata": {"name": "ui-job"},
            "spec": {"jaxReplicaSpecs": {"Worker": {
                "replicas": 1,
                "template": {"spec": {"containers": [{
                    "name": "jax",
                    "command": [PY, "-c", "print('from-the-ui')"],
                }]}},
            }}},
        })
        uid = out["uid"]
        deadline = time.time() + 30
        while time.time() < deadline:
            if cluster.status(uid).phase == "Succeeded":
                break
            time.sleep(0.05)
        assert cluster.status(uid).phase == "Succeeded"
        assert "from-the-ui" in call("GET", f"/api/jobs/{uid}/logs")
        call("DELETE", f"/api/jobs/{uid}")

        # notebook CRUD
        call("POST", "/api/notebooks", {"name": "ui-nb"})
        assert any(
            n["name"] == "ui-nb" for n in call("GET", "/api/notebooks")
        )
        call("DELETE", "/api/notebooks/ui-nb")

        # tensorboard CRUD
        call("POST", "/api/tensorboards",
             {"name": "ui-tb", "logdir": str(tmp_path)})
        assert any(
            t["name"] == "ui-tb" for t in call("GET", "/api/tensorboards")
        )
        call("DELETE", "/api/tensorboards/ui-tb")

        # bad manifest is a 400, unknown uid a 404 — not a 500
        for method, path, body, code in (
            ("POST", "/api/jobs", {"kind": "Nope"}, 400),
            ("DELETE", "/api/jobs/ghost", None, 404),
        ):
            try:
                call(method, path, body)
                raise AssertionError("expected HTTPError")
            except urllib.error.HTTPError as e:
                assert e.code == code


def test_dashboard_experiments_and_pipelines_tabs(cluster, tmp_path):
    """Katib-UI / KFP-frontend analogs: experiment and pipeline-run views
    backed by the persistent tune DB and lineage store."""
    from kubeflow_tpu.pipelines.metadata import LineageStore
    from kubeflow_tpu.tune.db import TrialDB
    from kubeflow_tpu.tune.spec import Trial, TrialAssignment, TrialState

    db = TrialDB(str(tmp_path / "t.db"))
    for i, state in enumerate(
        (TrialState.SUCCEEDED, TrialState.SUCCEEDED, TrialState.FAILED)
    ):
        t = Trial(assignment=TrialAssignment({"lr": 0.1 * (i + 1)},
                                             trial_id=f"t{i}"))
        t.state = state
        t.metrics = {"loss": float(i)}
        db.record_trial("sweep", t)

    store = LineageStore(str(tmp_path / "l.db"))
    e1 = store.begin_execution("run-1", "prep", "prep-comp")
    store.finish_execution(e1, state="Succeeded")
    e2 = store.begin_execution("run-1", "train", "train-comp")
    store.finish_execution(e2, state="Succeeded")

    with DashboardServer(cluster, tune_db=db, lineage=store) as dash:
        exps = json.loads(
            urllib.request.urlopen(dash.url + "/api/experiments").read()
        )
        assert exps == [{"name": "sweep", "trials": 3, "succeeded": 2,
                         "failed": 1, "running": 0,
                         "updated": exps[0]["updated"]}]
        trials = json.loads(
            urllib.request.urlopen(
                dash.url + "/api/experiments/sweep/trials"
            ).read()
        )
        assert len(trials) == 3 and trials[0]["parameters"]["lr"] == 0.1
        runs = json.loads(
            urllib.request.urlopen(dash.url + "/api/pipelines").read()
        )
        assert runs[0]["run_id"] == "run-1"
        assert runs[0]["state"] == "Succeeded" and runs[0]["tasks"] == 2
        tasks = json.loads(
            urllib.request.urlopen(
                dash.url + "/api/pipelines/run-1/tasks"
            ).read()
        )
        assert [t["task"] for t in tasks] == ["prep", "train"]
        summary = json.loads(
            urllib.request.urlopen(dash.url + "/api/summary").read()
        )
        assert summary["experiments"] == 1
        assert summary["pipeline_runs"] == 1


def test_dashboard_rejects_hostile_names(cluster):
    nb = NotebookController(cluster)
    with DashboardServer(cluster, notebooks=nb) as dash:
        req = urllib.request.Request(
            dash.url + "/api/notebooks",
            method="POST",
            data=json.dumps({"name": "<img src=x onerror=alert(1)>"}).encode(),
            headers={"content-type": "application/json"},
        )
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400


def test_dashboard_csrf_guard(cluster):
    """Cross-site no-preflight vehicles are rejected: non-JSON POST -> 415,
    non-local Host -> 403."""
    with DashboardServer(cluster) as dash:
        req = urllib.request.Request(
            dash.url + "/api/jobs", method="POST",
            data=b"kind=JAXJob", headers={"content-type": "text/plain"},
        )
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 415")
        except urllib.error.HTTPError as e:
            assert e.code == 415
        req = urllib.request.Request(
            dash.url + "/api/jobs", method="POST",
            data=json.dumps({"kind": "JAXJob"}).encode(),
            headers={"content-type": "application/json",
                     "Host": "evil.example.com"},
        )
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 403")
        except urllib.error.HTTPError as e:
            assert e.code == 403


def test_dashboard_pipeline_dag_view(cluster, tmp_path):
    """The KFP run-graph analog: the dashboard serves the run's DAG
    (structure + live task states) through a wired PipelineAPIServer,
    and degrades to {} for unknown runs or when unwired."""
    from kubeflow_tpu.pipelines import (
        ArtifactStore,
        LineageStore,
        PipelineAPIServer,
        PipelineRunner,
        StepCache,
        compile_pipeline,
        component,
        pipeline,
    )

    @component
    def left() -> int:
        return 1

    @component
    def right() -> int:
        return 2

    @component
    def join(a: int, b: int) -> int:
        return a + b

    @pipeline(name="diamond")
    def diamond():
        a = left()
        b = right()
        join(a=a.output, b=b.output)

    lineage = LineageStore(str(tmp_path / "l.db"))
    runner = PipelineRunner(
        artifact_store=ArtifactStore(str(tmp_path / "a")),
        cache=StepCache(str(tmp_path / "c")),
        lineage=lineage,
    )
    api = PipelineAPIServer(runner).start()
    try:
        rid = api.create_run(compile_pipeline(diamond), {})
        deadline = time.time() + 60
        while api.get_run(rid).state in ("PENDING", "RUNNING"):
            assert time.time() < deadline
            time.sleep(0.05)
        with DashboardServer(
            cluster, lineage=lineage, pipeline_api=api
        ) as dash:
            dag = json.loads(
                urllib.request.urlopen(
                    dash.url + f"/api/pipelines/{rid}/dag"
                ).read()
            )
            nodes = {t["name"]: t for t in dag["tasks"]}
            assert nodes["join"]["deps"] == ["left", "right"]
            assert all(t["state"] == "SUCCEEDED" for t in dag["tasks"])
            # unknown run → {} (the SPA hides the graph panel)
            empty = json.loads(
                urllib.request.urlopen(
                    dash.url + "/api/pipelines/nope/dag"
                ).read()
            )
            assert empty == {}
            # the SPA ships the renderer
            html = urllib.request.urlopen(dash.url + "/").read().decode()
            assert "drawDag" in html
    finally:
        api.stop()


def test_volume_controller_crud_and_protection(tmp_path):
    """PVC analog: create/list/delete with in-use protection, quota at
    mount, PVC-manifest parsing (SURVEY.md §2.5 volumes app row)."""
    import os

    from kubeflow_tpu.platform.volumes import VolumeController, VolumeSpec

    vc = VolumeController(str(tmp_path / "vols"))
    path = vc.create(VolumeSpec(name="data", size_mb=1))
    assert os.path.isdir(path)
    with pytest.raises(ValueError, match="already exists"):
        vc.create(VolumeSpec(name="data"))
    with pytest.raises(ValueError, match="DNS-1123"):
        VolumeSpec(name="Bad_Name").validate()

    # mount wires the env contract and protects deletion
    p, env = vc.mount("data", consumer="nb/alice")
    assert p == path and env == {"KFT_VOLUME_DATA": path}
    with pytest.raises(ValueError, match="in use"):
        vc.delete("data")
    # quota: exceed 1 MB then try to mount again
    with open(os.path.join(path, "big.bin"), "wb") as f:
        f.write(b"x" * (2 * 2**20))
    with pytest.raises(ValueError, match="over quota"):
        vc.mount("data", consumer="job/b")
    vc.unmount("data", consumer="nb/alice")
    vc.delete("data")
    assert not os.path.exists(path)
    with pytest.raises(KeyError):
        vc.get("data")

    # PVC manifest shape accepted 1:1
    spec = VolumeSpec.from_manifest({
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": "ws", "namespace": "team-a"},
        "spec": {"resources": {"requests": {"storage": "2Gi"}}},
    })
    assert spec.size_mb == 2048 and spec.namespace == "team-a"
    from kubeflow_tpu.platform import manifests as mfs

    assert mfs.parse({
        "kind": "PersistentVolumeClaim", "metadata": {"name": "x"},
        "spec": {"resources": {"requests": {"storage": "512Mi"}}},
    }).size_mb == 512


def test_volume_namespace_traversal_rejected_and_restart_recovers(tmp_path):
    import os

    from kubeflow_tpu.platform.volumes import VolumeController, VolumeSpec

    root = tmp_path / "vols"
    vc = VolumeController(str(root))
    # path traversal via namespace must die at validation, nothing created
    with pytest.raises(ValueError, match="DNS-1123"):
        vc.create(VolumeSpec(name="evil", namespace="../../outside"))
    assert not (tmp_path / "outside").exists()
    with pytest.raises(ValueError):
        vc.path("../../outside", "evil")

    # durability: a new controller over the same root re-registers volumes
    vc.create(VolumeSpec(name="keep", size_mb=7))
    vc2 = VolumeController(str(root))
    assert vc2.get("keep").size_mb == 7
    with pytest.raises(ValueError, match="already exists"):
        vc2.create(VolumeSpec(name="keep"))
    assert vc2.count() == 1


def test_volume_recover_serializes_with_live_mutations(tmp_path):
    """Race regression (kft lint lock-discipline finding): ``_recover``
    used to repopulate ``self._volumes`` without the controller lock, so a
    re-scan racing a live ``create``/``bind`` could interleave with other
    mutators mid-update. Now recovery holds the lock: while another thread
    owns it, ``_recover`` must demonstrably wait."""
    import threading

    from kubeflow_tpu.platform.volumes import VolumeController, VolumeSpec

    root = tmp_path / "vols"
    vc = VolumeController(str(root))
    vc.create(VolumeSpec(name="keep", size_mb=7))

    recovered = threading.Event()

    def rescan():
        vc._recover()
        recovered.set()

    with vc._lock:  # a mutator mid-critical-section
        t = threading.Thread(target=rescan, daemon=True)
        t.start()
        assert not recovered.wait(0.2), "_recover entered without the lock"
    t.join(timeout=5)
    assert recovered.is_set()
    assert vc.get("keep").size_mb == 7  # rescan kept the durable volume


def test_dashboard_job_post_rejects_non_job_kinds(cluster):
    with DashboardServer(cluster) as dash:
        req = urllib.request.Request(
            dash.url + "/api/jobs",
            data=json.dumps({
                "kind": "PersistentVolumeClaim", "metadata": {"name": "x"},
                "spec": {"resources": {"requests": {"storage": "1Gi"}}},
            }).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 400  # clean contract, not a 500


def test_dashboard_volumes_crud(cluster, tmp_path):
    from kubeflow_tpu.platform.volumes import VolumeController

    vc = VolumeController(str(tmp_path / "vols"))
    with DashboardServer(cluster, volumes=vc) as dash:
        req = urllib.request.Request(
            dash.url + "/api/volumes",
            data=json.dumps({"name": "scratch", "size_mb": 64}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req) as r:
            out = json.loads(r.read())
        assert out["name"] == "scratch"
        rows = json.loads(
            urllib.request.urlopen(dash.url + "/api/volumes").read()
        )
        assert rows[0]["name"] == "scratch" and rows[0]["size_mb"] == 64
        summary = json.loads(
            urllib.request.urlopen(dash.url + "/api/summary").read()
        )
        assert summary["volumes"] == 1
        req = urllib.request.Request(
            dash.url + "/api/volumes/scratch", method="DELETE"
        )
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read())["deleted"] == "scratch"
        assert json.loads(
            urllib.request.urlopen(dash.url + "/api/volumes").read()
        ) == []
