"""Multislice DCN validation (VERDICT r1 item 7; SURVEY.md §2.7 "DCN" row):
two jax.distributed CPU process groups stand in for two TPU slices — mesh
with a leading dcn axis, DP across slices, TP/FSDP within, placement
asserted inside the worker (kubeflow_tpu/examples/multislice.py)."""

import sys
from pathlib import Path

import pytest

from kubeflow_tpu.orchestrator import (
    JobSpec,
    LocalCluster,
    ReplicaSpec,
    TPURequest,
)
from kubeflow_tpu.orchestrator.envwire import WiringConfig
from kubeflow_tpu.orchestrator.resources import Fleet

REPO = str(Path(__file__).resolve().parent.parent)
PY = sys.executable


@pytest.mark.slow
def test_two_virtual_slices_dp_across_tp_within(tmp_path):
    cluster = LocalCluster(
        fleet=Fleet.homogeneous(2, "2x2"),
        wiring=WiringConfig(platform="cpu_sim", devices_per_worker=4),
        base_dir=str(tmp_path),
        resync_period=0.05,
    )
    with cluster:
        job = JobSpec(
            name="multislice",
            replicas={
                "worker": ReplicaSpec(
                    replicas=2,
                    command=(
                        PY, "-m", "kubeflow_tpu.examples.multislice",
                        "--steps", "4", "--seq-len", "64",
                    ),
                    env={"PYTHONPATH": REPO},
                    tpu=TPURequest(chips=4),
                )
            },
        )
        uid = cluster.submit(job)
        status = cluster.wait(uid, timeout=600)
        log0 = cluster.logs(uid, "worker", 0)
        log1 = cluster.logs(uid, "worker", 1)
        assert status.phase == "Succeeded", f"rank0:\n{log0}\nrank1:\n{log1}"
        # both processes confirmed every DCN block is exactly one process
        assert "dcn placement ok: 2 slices x 4 devices" in log0
        assert "dcn placement ok: 2 slices x 4 devices" in log1
        # the cross-slice collective actually crossed slices
        assert "cross-slice psum ok" in log0
        # DP-across/TP-within training completed
        assert "multislice training ok: steps=4" in log0
