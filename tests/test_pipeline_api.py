"""Pipelines REST API (pipelines/api.py): upload/run/watch over HTTP —
the KFP API-server surface (SURVEY.md §2.4 API-server row), plus the
`kft pipeline` CLI spellings of the same flows."""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.pipelines import (
    ArtifactStore,
    Dataset,
    Input,
    LineageStore,
    Output,
    PipelineRunner,
    StepCache,
    compile_pipeline,
    component,
    pipeline,
)
from kubeflow_tpu.pipelines.api import PipelineAPIServer


@component
def produce(n: int, out: Output[Dataset]) -> None:
    with open(out.path, "w") as f:
        f.write(",".join(str(i) for i in range(n)))


@component
def consume(data: Input[Dataset], scale: int) -> int:
    with open(data.path) as f:
        return scale * sum(int(x) for x in f.read().split(","))


@pipeline(name="api-pipeline", description="produce → consume")
def api_pipeline(n: int = 4, scale: int = 1):
    d = produce(n=n)
    consume(data=d.output, scale=scale)


@component
def boom() -> int:
    raise RuntimeError("kaboom")


@pipeline(name="boom-pipeline")
def boom_pipeline():
    boom()


def _req(method: str, url: str, body: dict | None = None) -> dict:
    req = urllib.request.Request(
        url,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method=method,
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


@pytest.fixture()
def api(tmp_path):
    lineage = LineageStore(str(tmp_path / "mlmd.db"))
    runner = PipelineRunner(
        artifact_store=ArtifactStore(str(tmp_path / "artifacts")),
        cache=StepCache(str(tmp_path / "cache")),
        lineage=lineage,
    )
    server = PipelineAPIServer(runner).start()
    yield server, lineage
    server.stop()


def _wait_terminal(base: str, rid: str, timeout: float = 60.0) -> dict:
    deadline = time.monotonic() + timeout
    while True:
        rec = _req("GET", f"{base}/apis/v2beta1/runs/{rid}")
        if rec["state"] not in ("PENDING", "RUNNING"):
            return rec
        assert time.monotonic() < deadline, rec
        time.sleep(0.05)


def test_upload_run_watch_e2e(api):
    """The VERDICT 'done' bar: submit a pipeline and watch a run over
    HTTP end to end."""
    server, lineage = api
    base = server.url
    ir = compile_pipeline(api_pipeline)

    up = _req("POST", f"{base}/apis/v2beta1/pipelines", {"spec": ir.to_dict()})
    assert up["name"] == "api-pipeline" and up["tasks"] == 2

    listed = _req("GET", f"{base}/apis/v2beta1/pipelines")["pipelines"]
    assert [p["name"] for p in listed] == ["api-pipeline"]
    got = _req("GET", f"{base}/apis/v2beta1/pipelines/api-pipeline")
    assert got["spec"]["name"] == "api-pipeline"

    run = _req(
        "POST", f"{base}/apis/v2beta1/runs",
        {"pipeline": "api-pipeline", "parameters": {"n": 3, "scale": 10}},
    )
    rec = _wait_terminal(base, run["run_id"])
    assert rec["state"] == "SUCCEEDED", rec
    assert rec["tasks"]["produce"]["state"] == "SUCCEEDED"
    assert rec["tasks"]["consume"]["state"] == "SUCCEEDED"
    assert rec["parameters"] == {"n": 3, "scale": 10}

    runs = _req("GET", f"{base}/apis/v2beta1/runs")["runs"]
    assert runs[0]["run_id"] == run["run_id"]

    # the DAG view: structure captured at submit + live task states
    dag = _req("GET", f"{base}/apis/v2beta1/runs/{run['run_id']}/dag")
    nodes = {t["name"]: t for t in dag["tasks"]}
    assert nodes["produce"]["deps"] == []
    assert nodes["consume"]["deps"] == ["produce"]
    assert all(t["state"] == "SUCCEEDED" for t in dag["tasks"])

    # the dashboard's read-only pipelines tab shares this LineageStore:
    # a run submitted over the API is visible there — with the right
    # terminal state (regression: the rollup once matched 'Succeeded'
    # while the runner writes 'SUCCEEDED', showing every run as Running)
    dash = {r["run_id"]: r for r in lineage.runs()}
    assert dash[run["run_id"]]["state"] == "Succeeded"
    assert dash[run["run_id"]]["succeeded"] == 2

    # a failing pipeline reports FAILED with the task error
    _req("POST", f"{base}/apis/v2beta1/pipelines",
         {"spec": compile_pipeline(boom_pipeline).to_dict()})
    run2 = _req("POST", f"{base}/apis/v2beta1/runs",
                {"pipeline": "boom-pipeline"})
    rec2 = _wait_terminal(base, run2["run_id"])
    assert rec2["state"] == "FAILED"
    assert "kaboom" in rec2["tasks"]["boom"]["error"]

    deleted = _req("DELETE", f"{base}/apis/v2beta1/pipelines/api-pipeline")
    assert deleted["deleted"] == "api-pipeline"


def test_api_error_contract(api):
    server, _ = api
    base = server.url

    with pytest.raises(urllib.error.HTTPError) as e:
        _req("POST", f"{base}/apis/v2beta1/runs", {"pipeline": "nope"})
    assert e.value.code == 404

    ir = compile_pipeline(api_pipeline)
    _req("POST", f"{base}/apis/v2beta1/pipelines", {"spec": ir.to_dict()})
    # unknown parameter rejected AT SUBMIT (not inside the run thread)
    with pytest.raises(urllib.error.HTTPError) as e:
        _req("POST", f"{base}/apis/v2beta1/runs",
             {"pipeline": "api-pipeline", "parameters": {"bogus": 1}})
    assert e.value.code == 404  # KeyError contract: unknown name

    with pytest.raises(urllib.error.HTTPError) as e:
        _req("GET", f"{base}/apis/v2beta1/runs/deadbeef")
    assert e.value.code == 404

    # a cyclic spec is rejected at upload AND at inline-run submit
    bad = ir.to_dict()
    bad["tasks"][0]["after"] = [bad["tasks"][1]["name"]]
    bad["tasks"][1]["after"] = [bad["tasks"][0]["name"]]
    for path, body in (
        ("/apis/v2beta1/pipelines", {"spec": bad}),
        ("/apis/v2beta1/runs", {"spec": bad}),
        ("/apis/v2beta1/recurringruns", {"spec": bad, "interval_s": 1}),
    ):
        with pytest.raises(urllib.error.HTTPError) as e:
            _req("POST", f"{base}{path}", body)
        assert e.value.code == 400, path

    # malformed requests (missing fields) are 400, not 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _req("POST", f"{base}/apis/v2beta1/runs", {"parameters": {}})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _req("POST", f"{base}/apis/v2beta1/recurringruns",
             {"spec": ir.to_dict()})
    assert e.value.code == 400


def test_recurring_crud_over_http(api):
    server, _ = api
    base = server.url
    ir = compile_pipeline(api_pipeline)

    rr = _req(
        "POST", f"{base}/apis/v2beta1/recurringruns",
        {"spec": ir.to_dict(), "interval_s": 0.1, "max_runs": 2,
         "parameters": {"n": 2}},
    )
    uid = rr["uid"]
    deadline = time.monotonic() + 60
    while True:
        got = _req("GET", f"{base}/apis/v2beta1/recurringruns/{uid}")
        if got["fired"] >= 2 and len(got["history"]) >= 2:
            break
        assert time.monotonic() < deadline, got
        time.sleep(0.05)
    assert all(h["state"] == "SUCCEEDED" for h in got["history"])

    _req("POST", f"{base}/apis/v2beta1/recurringruns/{uid}:pause")
    assert _req("GET", f"{base}/apis/v2beta1/recurringruns/{uid}")["paused"]
    _req("POST", f"{base}/apis/v2beta1/recurringruns/{uid}:resume")
    assert not _req("GET", f"{base}/apis/v2beta1/recurringruns/{uid}")["paused"]

    listed = _req("GET", f"{base}/apis/v2beta1/recurringruns")
    assert [r["uid"] for r in listed["recurring_runs"]] == [uid]
    _req("DELETE", f"{base}/apis/v2beta1/recurringruns/{uid}")
    with pytest.raises(urllib.error.HTTPError):
        _req("GET", f"{base}/apis/v2beta1/recurringruns/{uid}")


def test_inline_spec_run(api):
    """`kft pipeline run -f` one-shot path: no upload, spec inline."""
    server, _ = api
    base = server.url
    ir = compile_pipeline(api_pipeline)
    run = _req("POST", f"{base}/apis/v2beta1/runs",
               {"spec": ir.to_dict(), "parameters": {"n": 2}})
    rec = _wait_terminal(base, run["run_id"])
    assert rec["state"] == "SUCCEEDED"


PIPELINE_PY = '''
from kubeflow_tpu.pipelines import component, pipeline

@component
def double(x: int) -> int:
    return 2 * x

@component
def inc(x: int) -> int:
    return x + 1

@pipeline(name="cli-pipeline")
def cli_pipeline(x: int = 3):
    d = double(x=x)
    inc(x=d.output)
'''


def test_cli_compile_and_local_run(tmp_path, capsys):
    from kubeflow_tpu.cli import main

    src = tmp_path / "pipe.py"
    src.write_text(PIPELINE_PY)
    out_json = tmp_path / "pipe.json"
    assert main(["pipeline", "compile", "-f", str(src),
                 "-o", str(out_json)]) == 0
    ir_doc = json.loads(out_json.read_text())
    assert ir_doc["name"] == "cli-pipeline"

    # local in-process run from the COMPILED artifact, param override
    rc = main(["pipeline", "run", "-f", str(out_json), "-p", "x=5",
               "--artifacts", str(tmp_path / "work")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "task/double: SUCCEEDED" in out
    assert ": SUCCEEDED" in out.splitlines()[-1]


def test_cli_run_without_file_is_usage_error(capsys):
    from kubeflow_tpu.cli import main

    with pytest.raises(SystemExit, match="-f is required without"):
        main(["pipeline", "run", "--name", "foo"])


def test_cli_upload_and_server_run(tmp_path, api, capsys):
    from kubeflow_tpu.cli import main

    server, _ = api
    src = tmp_path / "pipe.py"
    src.write_text(PIPELINE_PY)
    assert main(["pipeline", "upload", "-f", str(src),
                 "--server", server.url]) == 0
    rc = main(["pipeline", "run", "--name", "cli-pipeline",
               "--server", server.url, "-p", "x=4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "uploaded" in out and "SUCCEEDED" in out
    assert main(["pipeline", "list", "--server", server.url]) == 0
    assert "cli-pipeline" in capsys.readouterr().out
