"""core/mesh: logical-axis specs, topology mapping, mesh construction."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.core.mesh import (
    Axis,
    MeshSpec,
    build_mesh,
    per_device_batch,
    single_device_mesh,
    slice_topology,
)


def test_slice_topology_known_v5e_sizes():
    assert slice_topology(8) == (2, 4)
    assert slice_topology(16) == (4, 4)
    assert slice_topology(256) == (16, 16)


def test_slice_topology_fallback_near_square():
    assert slice_topology(12) == (3, 4)
    assert slice_topology(7) == (1, 7)


def test_meshspec_validation():
    MeshSpec(data=8).validate(8)
    with pytest.raises(ValueError):
        MeshSpec(data=4).validate(8)
    with pytest.raises(ValueError):
        MeshSpec(data=0).validate()
    with pytest.raises(ValueError):
        MeshSpec.from_dict({"nope": 2})


def test_meshspec_roundtrip():
    spec = MeshSpec(data=2, model=4)
    assert MeshSpec.from_dict(spec.to_dict()) == spec
    assert spec.total_devices == 8


def test_build_mesh_dp(devices8):
    mesh = build_mesh(MeshSpec.data_parallel(8))
    assert mesh.shape[Axis.DATA] == 8
    assert mesh.shape[Axis.MODEL] == 1
    assert mesh.devices.size == 8


def test_build_mesh_2d(devices8):
    mesh = build_mesh(MeshSpec(data=2, model=4))
    assert mesh.shape[Axis.DATA] == 2
    assert mesh.shape[Axis.MODEL] == 4


def test_build_mesh_hybrid_dcn(devices8):
    # 2 "slices" of 4 chips each: dcn_data folds into the data axis position.
    mesh = build_mesh(MeshSpec(model=4, dcn_data=2))
    assert mesh.shape[Axis.DATA] == 2
    assert mesh.shape[Axis.MODEL] == 4


def test_sharded_matmul_on_mesh(devices8):
    """End-to-end: shard a matmul over data x model and check numerics."""
    mesh = build_mesh(MeshSpec(data=2, model=4))
    x = np.random.RandomState(0).randn(16, 32).astype(np.float32)
    w = np.random.RandomState(1).randn(32, 64).astype(np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(Axis.DATA, None)))
    ws = jax.device_put(w, NamedSharding(mesh, P(None, Axis.MODEL)))
    out = jax.jit(jnp.dot)(xs, ws)
    np.testing.assert_allclose(np.asarray(out), x @ w, rtol=1e-4)


def test_per_device_batch():
    assert per_device_batch(64, MeshSpec(data=2, fsdp=4)) == 8
    with pytest.raises(ValueError):
        per_device_batch(10, MeshSpec(data=4))


def test_single_device_mesh():
    mesh = single_device_mesh()
    assert mesh.devices.size == 1
