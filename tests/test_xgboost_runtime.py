"""XGBoost-format runtime (serve/xgboost_runtime.py): the device
fixed-depth traversal must match a straightforward host tree walk on
checkpoints written in XGBoost's published JSON format — including NaN
default routing, multiclass tree_info layout, and objective links.

xgboost itself is NOT installed (SURVEY.md §0); checkpoints here are
constructed in the documented ``save_model("*.json")`` schema, which is the
same bytes a reference user's booster would bring.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from kubeflow_tpu.serve.xgboost_runtime import (
    XGBoostRuntimeModel,
    build_device_predict,
    margin_numpy,
    parse_xgboost_json,
)


def _tree(split_indices, split_conditions, left, right, default_left):
    n = len(left)
    return {
        "split_indices": split_indices,
        "split_conditions": split_conditions,
        "left_children": left,
        "right_children": right,
        "default_left": default_left,
        "base_weights": [0.0] * n,
        "tree_param": {"num_nodes": str(n)},
    }


def _checkpoint(
    trees, tree_info=None, *, num_class=0, num_feature, base_score=0.5,
    objective="reg:squarederror",
):
    return {
        "version": [2, 0, 0],
        "learner": {
            "learner_model_param": {
                "base_score": str(base_score),
                "num_class": str(num_class),
                "num_feature": str(num_feature),
            },
            "objective": {"name": objective},
            "gradient_booster": {
                "model": {
                    "trees": trees,
                    "tree_info": tree_info or [0] * len(trees),
                }
            },
        },
    }


def _write(tmp_path, doc, name="model.json"):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


# node 0: x[0] < 0.5 ? node1(leaf +1) : node2(leaf -3); NaN goes left
STUMP = _tree([0, 0, 0], [0.5, 1.0, -3.0], [1, -1, -1], [2, -1, -1],
              [True, False, False])


def test_single_stump_regression(tmp_path):
    path = _write(tmp_path, _checkpoint([STUMP], num_feature=1, base_score=2.0))
    b = parse_xgboost_json(path)
    fwd = build_device_predict(b)
    x = np.asarray([[0.0], [0.9], [np.nan]], np.float32)
    # base_score is the margin intercept for squared error
    np.testing.assert_allclose(
        np.asarray(fwd(x)), [3.0, -1.0, 3.0], rtol=1e-6
    )


def test_depth_and_missing_routing_match_host_walk(tmp_path):
    # deeper tree exercising both NaN directions
    t = _tree(
        [1, 0, 2, 0, 0, 0, 0],
        [0.0, -1.0, 5.0, 0.25, -0.5, 1.5, -2.25],
        [1, 3, 5, -1, -1, -1, -1],
        [2, 4, 6, -1, -1, -1, -1],
        [False, True, False, False, False, False, False],
    )
    path = _write(tmp_path, _checkpoint([t, STUMP], num_feature=3))
    b = parse_xgboost_json(path)
    fwd = build_device_predict(b, output="margin")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 3)).astype(np.float32)
    x[rng.random(x.shape) < 0.25] = np.nan
    np.testing.assert_allclose(
        np.asarray(fwd(x))[:, 0], margin_numpy(b, x)[:, 0], rtol=1e-5
    )


def _random_checkpoint(rng, *, n_trees, num_feature, num_class=0,
                       objective="reg:squarederror", base_score=0.5):
    """Random well-formed trees: internal nodes in BFS order, ragged sizes."""
    trees = []
    for _ in range(n_trees):
        n_internal = int(rng.integers(1, 8))
        n = 2 * n_internal + 1
        left = [-1] * n
        right = [-1] * n
        si = [0] * n
        sc = [0.0] * n
        dl = [False] * n
        for i in range(n_internal):
            left[i], right[i] = 2 * i + 1, 2 * i + 2
            si[i] = int(rng.integers(0, num_feature))
            sc[i] = float(rng.normal())
            dl[i] = bool(rng.random() < 0.5)
        for i in range(n_internal, n):
            sc[i] = float(rng.normal())
        trees.append(_tree(si, sc, left, right, dl))
    info = (
        [i % num_class for i in range(n_trees)] if num_class else None
    )
    return _checkpoint(
        trees, info, num_class=num_class, num_feature=num_feature,
        base_score=base_score, objective=objective,
    )


def test_fuzz_random_forests_match_host_walk(tmp_path):
    rng = np.random.default_rng(7)
    for trial in range(5):
        doc = _random_checkpoint(rng, n_trees=11, num_feature=5)
        b = parse_xgboost_json(_write(tmp_path, doc, f"m{trial}.json"))
        x = rng.normal(size=(32, 5)).astype(np.float32)
        x[rng.random(x.shape) < 0.2] = np.nan
        got = np.asarray(build_device_predict(b, output="margin")(x))[:, 0]
        np.testing.assert_allclose(got, margin_numpy(b, x)[:, 0], rtol=1e-4)


def test_binary_logistic_outputs_probability(tmp_path):
    path = _write(
        tmp_path,
        _checkpoint([STUMP], num_feature=1, base_score=0.5,
                    objective="binary:logistic"),
    )
    b = parse_xgboost_json(path)
    x = np.asarray([[0.0], [0.9]], np.float32)
    prob = np.asarray(build_device_predict(b)(x))
    # base_score 0.5 → margin intercept logit(0.5)=0; sigmoid(leaf sums)
    np.testing.assert_allclose(
        prob, 1.0 / (1.0 + np.exp(-np.asarray([1.0, -3.0]))), rtol=1e-5
    )
    assert ((prob > 0) & (prob < 1)).all()


def test_multiclass_softmax_and_softprob(tmp_path):
    rng = np.random.default_rng(3)
    doc = _random_checkpoint(
        rng, n_trees=9, num_feature=4, num_class=3, objective="multi:softprob"
    )
    b = parse_xgboost_json(_write(tmp_path, doc))
    x = rng.normal(size=(16, 4)).astype(np.float32)
    probs = np.asarray(build_device_predict(b)(x))
    assert probs.shape == (16, 3)
    np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-5)
    margins = margin_numpy(b, x)
    np.testing.assert_array_equal(
        probs.argmax(-1), margins.argmax(-1)
    )
    # multi:softmax returns the class index directly
    doc["learner"]["objective"]["name"] = "multi:softmax"
    b2 = parse_xgboost_json(_write(tmp_path, doc, "m2.json"))
    cls = np.asarray(build_device_predict(b2)(x))
    np.testing.assert_array_equal(cls, margins.argmax(-1))


def test_runtime_model_lifecycle_and_validation(tmp_path):
    path = _write(tmp_path, _checkpoint([STUMP], num_feature=1))
    m = XGBoostRuntimeModel("gbt", str(tmp_path))
    m.load()
    assert m.ready
    out = m.postprocess(m.predict(m.preprocess({"instances": [[0.0]]})))
    np.testing.assert_allclose(out["predictions"], [1.5])  # 1.0 + 0.5 base
    with pytest.raises(ValueError, match="expects 1 features"):
        m.preprocess([[1.0, 2.0]])
    m.unload()
    assert not m.ready


def test_rejects_non_xgboost_json(tmp_path):
    p = tmp_path / "model.json"
    p.write_text(json.dumps({"not": "a booster"}))
    with pytest.raises(RuntimeError, match="not an XGBoost JSON checkpoint"):
        parse_xgboost_json(str(p))


def test_e2e_through_model_server(tmp_path):
    """xgboost format resolves from the default registry and answers REST."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kubeflow_tpu.serve.runtimes import default_registry
    from kubeflow_tpu.serve.server import ModelServer
    from kubeflow_tpu.serve.spec import ComponentSpec

    _write(tmp_path, _checkpoint([STUMP], num_feature=1))
    rt = default_registry().resolve(
        ComponentSpec(model_format="xgboost", storage_uri="unused")
    )
    model = rt.factory("gbt", str(tmp_path))
    model.load()
    server = ModelServer([model])

    async def roundtrip():
        async with TestClient(TestServer(server.build_app())) as client:
            r = await client.post(
                "/v1/models/gbt:predict", json={"instances": [[0.9], [0.0]]}
            )
            assert r.status == 200
            return await r.json()

    body = asyncio.run(roundtrip())
    np.testing.assert_allclose(body["predictions"], [-2.5, 1.5])


def test_categorical_splits_fail_closed(tmp_path):
    """enable_categorical boosters store category sets, not thresholds —
    serving them as numeric would be silently wrong. Must refuse to load."""
    t = dict(STUMP)
    t["split_type"] = [1, 0, 0]
    t["categories"] = [2, 5]
    path = _write(tmp_path, _checkpoint([t], num_feature=1))
    with pytest.raises(RuntimeError, match="categorical"):
        parse_xgboost_json(path)


def test_predict_buckets_batch_sizes(tmp_path):
    """Odd batch sizes pad to the next power-of-two compiled shape and
    slice back — answers identical to the exact-shape run."""
    rng = np.random.default_rng(5)
    doc = _random_checkpoint(rng, n_trees=5, num_feature=3)
    _write(tmp_path, doc)
    m = XGBoostRuntimeModel("gbt", str(tmp_path))
    m.load()
    x = rng.normal(size=(7, 3)).astype(np.float32)
    out = m.predict(x)
    assert out.shape[0] == 7
    np.testing.assert_allclose(out, margin_numpy(m.booster, x)[:, 0]
                               + 0.0, rtol=1e-4)
