"""Serving autoscaler (KPA analog) + cross-replica prefix-KV transfer.

Unit layers: the recommender's stable/panic/scale-to-zero state machine,
prom-text signal folding, hash-ring remap planning (previous-owner pull
targeting), engine prefix export/import, the controller's recommender-
backed ``autoscale_tick``, load-signal reset after a watchdog restart,
and the activator's autoscaler-facing gauges.

Chaos acceptance e2es (the ISSUE 11 criteria): an open-loop burst that
scales real replicas 1→3 (panic) and back down to zero with no
client-visible failure, scale-from-zero through the activator; and a
ring remap whose new replica recovers its prefix-hit rate by pulling KV
from the previous owner instead of re-prefilling."""

import asyncio
import time

import pytest

from kubeflow_tpu.autoscale.kpa import KPAConfig, KPARecommender, _Window
from kubeflow_tpu.autoscale.autoscaler import ServingAutoscaler
from kubeflow_tpu.autoscale.fleet import ReplicaFleet
from kubeflow_tpu.autoscale.kv_transfer import owner_of, plan_rebalance
from kubeflow_tpu.autoscale.signals import (
    GatewaySignalSource,
    ServiceSignals,
    fold_replica_metrics,
    metric_sum,
    parse_prom_text,
)
from kubeflow_tpu.gateway.router import HashRing, prefix_affinity_key
from kubeflow_tpu.obs.prom import REGISTRY
from kubeflow_tpu.serve.model import Model
from kubeflow_tpu.serve.server import (
    DataPlane,
    ModelServer,
    decode_prefix_entries,
    encode_prefix_entries,
)


def _metric(name, **labels):
    m = REGISTRY._metrics.get(name)
    if m is None:
        return 0.0
    child = m._children.get(tuple(sorted(labels.items())))
    return child.value if child else 0.0


# ------------------------------------------------------------------- KPA


def test_window_average_prunes_and_windows():
    w = _Window(10.0)
    for t, v in [(0.0, 4.0), (5.0, 2.0), (9.0, 6.0)]:
        w.observe(t, v)
    assert w.average(9.0, 10.0) == pytest.approx(4.0)
    assert w.average(9.0, 1.0) == pytest.approx(6.0)  # short window
    assert w.average(9.0, 0.5) == pytest.approx(6.0)
    w.observe(20.0, 8.0)  # t=0,5,9 now pruned (older than 10s)
    assert w.average(20.0, 10.0) == pytest.approx(8.0)
    assert w.average(30.0, 5.0) == 0.0  # empty window → no demand


def test_kpa_stable_scaling_and_rate_limits():
    cfg = KPAConfig(
        target=2.0, min_replicas=1, max_replicas=10,
        stable_window_s=10.0, panic_window_s=2.0,
        panic_threshold=10.0,  # effectively off for this test
        max_scale_down_rate=2.0,
    )
    rec = KPARecommender(cfg, clock=lambda: 0.0)
    rec.observe(8.0, now=1.0)
    assert rec.recommend(2, now=1.0).desired == 4  # ceil(8/2)
    # scale-down is rate-limited: from 8 ready it may halve at most
    rec2 = KPARecommender(cfg, clock=lambda: 0.0)
    rec2.observe(2.0, now=1.0)
    assert rec2.recommend(8, now=1.0).desired == 4  # floor(8/2), not 1
    # bounds clamp
    rec3 = KPARecommender(cfg, clock=lambda: 0.0)
    rec3.observe(100.0, now=1.0)
    assert rec3.recommend(4, now=1.0).desired == 10


def test_kpa_panic_mode_enters_scales_and_refuses_scale_down():
    cfg = KPAConfig(
        target=1.0, min_replicas=1, max_replicas=10,
        stable_window_s=20.0, panic_window_s=2.0, panic_threshold=2.0,
    )
    rec = KPARecommender(cfg, clock=lambda: 0.0)
    rec.observe(6.0, now=1.0)  # burst: 6 concurrent at 1 replica
    r = rec.recommend(1, now=1.0)
    assert r.panic and r.desired == 6
    # burst ends; panic persists a full stable window → no scale-down
    rec.observe(0.0, now=5.0)
    r = rec.recommend(6, now=5.0)
    assert r.panic and r.desired == 6
    # a stable window after the last panic signal, panic exits and the
    # (now decayed) stable average sizes the service back down
    rec.observe(0.0, now=22.0)
    r = rec.recommend(6, now=22.0)
    assert not r.panic
    assert r.desired == 3  # rate-limited: floor(6/2), not straight to 1


def test_kpa_scale_to_zero_grace_and_activation():
    cfg = KPAConfig(
        target=1.0, min_replicas=0, max_replicas=4,
        stable_window_s=10.0, panic_window_s=2.0,
        scale_to_zero_grace_s=5.0,
    )
    rec = KPARecommender(cfg, clock=lambda: 0.0)
    rec.observe(1.0, now=1.0)
    assert rec.recommend(1, now=1.0).desired == 1
    # idle but inside the grace window: the last replica is held
    rec.observe(0.0, now=4.0)
    assert rec.recommend(1, now=4.0).desired == 1
    # grace expired → zero
    rec.observe(0.0, now=12.0)
    assert rec.recommend(1, now=12.0).desired == 0
    # at zero with no demand it stays at zero
    rec.observe(0.0, now=13.0)
    assert rec.recommend(0, now=13.0).desired == 0
    # the activator's kick (parked demand) wakes it
    rec.activity(now=14.0)
    rec.observe(1.0, now=14.0)
    assert rec.recommend(0, now=14.0).desired == 1


def test_kpa_config_validation_and_manifest():
    with pytest.raises(ValueError):
        KPAConfig(target=0).validate()
    with pytest.raises(ValueError):
        KPAConfig(min_replicas=3, max_replicas=2).validate()
    with pytest.raises(ValueError):
        KPAConfig(panic_window_s=70.0, stable_window_s=60.0).validate()
    with pytest.raises(ValueError):
        KPAConfig(panic_threshold=0.5).validate()
    cfg = KPAConfig.from_manifest({
        "target": 4, "minReplicas": 0, "maxReplicas": 6,
        "stableWindowS": 30, "panicWindowS": 3, "panicThreshold": 1.5,
        "scaleToZeroGraceS": 10,
    })
    assert cfg.target == 4.0 and cfg.min_replicas == 0
    assert cfg.max_replicas == 6 and cfg.panic_threshold == 1.5


# -------------------------------------------------------------- signals


def test_parse_prom_text_and_fold():
    text = "\n".join([
        "# HELP kft_server_inflight requests executing",
        'kft_server_inflight{model="m"} 3',
        'kft_server_inflight{model="n"} 2',
        'kft_server_queue_depth{model="m"} 4',
        'kft_engine_decode_gap_ms{model="m"} 12.5',
        "not a metric line {{{",
        "kft_bare_counter 7",
    ])
    parsed = parse_prom_text(text)
    assert metric_sum(parsed, "kft_server_inflight") == 5.0
    assert metric_sum(parsed, "kft_server_inflight", model="m") == 3.0
    assert metric_sum(parsed, "kft_bare_counter") == 7.0
    sig = ServiceSignals(activator_depth=2.0)
    fold_replica_metrics(sig, parsed)
    assert sig.inflight == 5.0 and sig.queue_depth == 4.0
    assert sig.decode_gap_ms == 12.5 and sig.replicas_reporting == 1
    assert sig.concurrency == 11.0  # inflight + queue + parked


# ------------------------------------------------- ring remap + planning


def _keys(n, seed=0):
    import random

    rng = random.Random(seed)
    out = []
    for _ in range(n):
        out.append(tuple(rng.randrange(2, 60) for _ in range(16)))
    return out


def test_hash_ring_remap_keeps_unmoved_keys_stable():
    """Consistent-hashing acceptance: adding a replica only moves keys TO
    it; removing one only moves the keys it owned."""
    a, b, c = "http://a", "http://b", "http://c"
    keys = _keys(200)
    two = HashRing((a, b))
    three = HashRing((a, b, c))
    moved = 0
    for k in keys:
        o2 = two.pick(prefix_affinity_key(k))
        o3 = three.pick(prefix_affinity_key(k))
        if o2 != o3:
            assert o3 == c, (o2, o3)  # movement only toward the newcomer
            moved += 1
    assert 0 < moved < len(keys)  # some moved, most did not
    for k in keys:  # removal: survivors keep everything they had
        o3 = three.pick(prefix_affinity_key(k))
        o2 = two.pick(prefix_affinity_key(k))
        if o3 != c:
            assert o2 == o3


def test_plan_rebalance_scale_up_pulls_from_previous_owner_only():
    a, b, c = "http://a", "http://b", "http://c"
    keys = _keys(120, seed=1)
    two = HashRing((a, b))
    # steady state before the remap: every key lives on its 2-ring owner
    index = {a: [], b: []}
    for k in keys:
        index[two.pick(prefix_affinity_key(k))].append(k)
    plan = plan_rebalance(index, [a, b, c])
    assert plan, "remap moved nothing — ring fixture broken"
    three = HashRing((a, b, c))
    planned = set()
    for t in plan:
        assert t.dest == c  # scale-up: only the newcomer gains keys
        for k in t.keys:
            # the pull source IS the previous owner (where the KV lives)
            assert t.source == two.pick(prefix_affinity_key(k))
            assert three.pick(prefix_affinity_key(k)) == c
            planned.add(k)
    # completeness: every key the new ring assigns to c is planned
    want = {k for k in keys if three.pick(prefix_affinity_key(k)) == c}
    assert planned == want
    # unmoved keys never transfer
    assert not any(
        three.pick(prefix_affinity_key(k)) != c
        for t in plan for k in t.keys
    )


def test_plan_rebalance_dedups_and_handles_scale_down():
    a, b, c = "http://a", "http://b", "http://c"
    keys = _keys(60, seed=2)
    three = HashRing((a, b, c))
    index = {a: [], b: [], c: []}
    for k in keys:
        index[three.pick(prefix_affinity_key(k))].append(k)
    # a key resident on BOTH survivors that the owner already holds must
    # not transfer at all
    dup = index[a][0] if index[a] else index[b][0]
    index[b].append(dup)
    # scale-down: c leaves; its entries evacuate to the 2-ring owners
    plan = plan_rebalance(index, [a, b])
    two = HashRing((a, b))
    for t in plan:
        assert t.source == c  # only the leaver's keys move
        for k in t.keys:
            assert t.dest == two.pick(prefix_affinity_key(k))
            assert k != dup
    evacuated = {k for t in plan for k in t.keys}
    assert evacuated == set(map(tuple, index[c]))
    # each key transfers exactly once
    assert len(evacuated) == sum(len(t.keys) for t in plan)


def test_owner_of_matches_gateway_affinity_hash():
    urls = ("http://a", "http://b")
    ring = HashRing(urls)
    key = tuple(range(2, 18))
    assert owner_of(key, ring) == ring.pick(prefix_affinity_key(key))


# ------------------------------------------- engine export/import + wire


def _tiny_lm():
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        causal=True, max_seq_len=128, attn_impl="reference",
        dtype=jnp.float32,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, model, params


def _tiny_engine(cfg, model, params, **kw):
    from kubeflow_tpu.serve.engine import LMEngine

    kw.setdefault("prefix_cache_entries", 8)
    return LMEngine(
        model, cfg, params, max_batch=2, max_seq=96, chunk_steps=4,
        prefill_buckets=(32,), eos_id=cfg.vocab_size + 1, **kw
    ).start()


def test_engine_prefix_export_import_roundtrip_serves_hits():
    cfg, model, params = _tiny_lm()
    a = _tiny_engine(cfg, model, params)
    b = _tiny_engine(cfg, model, params)
    try:
        prompt = [5, 9, 13, 7] * 5  # 20 tokens → one 16-token entry
        out_a = a.submit(prompt, max_new_tokens=8)
        assert a.prefix_index() == [tuple(prompt[:16])]
        blob = encode_prefix_entries(a.export_prefix_entries())
        assert a.stats["prefix_exported"] == 1
        entries = decode_prefix_entries(blob)
        assert b.import_prefix_entries(entries) == 1
        assert b.stats["prefix_imported"] == 1
        # a re-import of a resident key is a no-op (local recency wins)
        assert b.import_prefix_entries(entries) == 0
        # the imported KV actually serves: same tokens, prefix hit, no
        # full re-prefill (16 of 20 prompt tokens reused)
        out_b = b.submit(prompt, max_new_tokens=8)
        assert out_b == out_a
        assert b.stats["prefix_hits"] == 1
        assert b.stats["prefix_tokens_reused"] == 16
    finally:
        a.stop()
        b.stop()


def test_engine_import_rejects_incompatible_entries():
    import numpy as np

    cfg, model, params = _tiny_lm()
    eng = _tiny_engine(cfg, model, params)
    try:
        layer = next(iter(eng.cache))
        good_shape = (1, cfg.kv_heads, 16, cfg.head_dim)
        bad = [
            # wrong head count
            (tuple(range(2, 18)), {
                name: {
                    "k": np.zeros((1, cfg.kv_heads + 1, 16, cfg.head_dim)),
                    "v": np.zeros((1, cfg.kv_heads + 1, 16, cfg.head_dim)),
                }
                for name in eng.cache
            }),
            # not a 16 multiple
            (tuple(range(2, 17)), {
                name: {"k": np.zeros(good_shape), "v": np.zeros(good_shape)}
                for name in eng.cache
            }),
            # missing layers
            (tuple(range(2, 18)), {
                layer: {"k": np.zeros(good_shape), "v": np.zeros(good_shape)}
            }),
        ]
        assert eng.import_prefix_entries(bad) == 0
        assert eng.prefix_cache_stats()["entries"] == 0
    finally:
        eng.stop()


def test_drop_prefix_cache_injector_and_fault_kind():
    from kubeflow_tpu.chaos import DropPrefixCache, FaultPlan
    from kubeflow_tpu.chaos.injectors import drop_prefix_cache

    plan = FaultPlan.from_dict({
        "faults": [{"kind": "DropPrefixCache", "model": "m"}]
    })
    assert isinstance(plan.faults[0], DropPrefixCache)
    assert plan.faults[0].model == "m"

    cfg, model, params = _tiny_lm()
    eng = _tiny_engine(cfg, model, params)
    try:
        eng.submit([5, 9, 13, 7] * 5, max_new_tokens=4)
        assert eng.prefix_cache_stats()["entries"] == 1
        before = _metric("kft_chaos_injected_total", kind="drop_prefix_cache")
        assert drop_prefix_cache(eng) == 1
        assert eng.prefix_cache_stats()["entries"] == 0
        assert eng.prefix_cache_stats()["tokens_stored"] == 0
        assert _metric(
            "kft_chaos_injected_total", kind="drop_prefix_cache"
        ) == before + 1
    finally:
        eng.stop()


# ----------------------------------------------- controller (satellite)


def test_controller_autoscale_tick_recommender_and_reapply_preserves_scale(
    tmp_path,
):
    from kubeflow_tpu.serve.controller import InferenceServiceController
    from kubeflow_tpu.serve.model import EchoModel
    from kubeflow_tpu.serve.spec import (
        InferenceServiceSpec,
        PredictorSpec,
        RuntimeRegistry,
        ServingRuntime,
    )

    reg = RuntimeRegistry()
    reg.register(ServingRuntime(
        "echo", ("echo",), lambda name, path, **kw: EchoModel(name)
    ))
    ctl = InferenceServiceController(
        reg, model_dir=str(tmp_path), idle_scale_to_zero_s=60.0
    )
    spec = InferenceServiceSpec("s", PredictorSpec(
        model_format="echo", min_replicas=1, max_replicas=4, scale_target=2,
    ))
    ctl.apply(spec)
    st = ctl.get("s")
    st.replicas.in_flight = 8
    assert ctl.autoscale_tick("s") == 4  # ceil(8/2), real recommender
    # the old reconcile stub clamped desired to min(1, max) on re-apply,
    # collapsing an autoscaled service — now it preserves current scale
    ctl.apply(InferenceServiceSpec("s", PredictorSpec(
        model_format="echo", min_replicas=1, max_replicas=4, scale_target=2,
    )))
    assert ctl.get("s").replicas.desired_replicas == 4
    # burst over: panic mode holds the scale for a stable window instead
    # of collapsing to 1 the instant in-flight drops
    st = ctl.get("s")
    st.replicas.in_flight = 0
    assert ctl.autoscale_tick("s") == 4


# -------------------------------------- load-signal reset (satellite)


def test_engine_restart_resets_load_signals():
    import jax

    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec

    cfg, _, params = _tiny_lm()
    m = LMEngineModel(
        "m", None, config=cfg, max_batch=2, chunk_steps=2,
        buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
        max_new_tokens=4, eos_id=cfg.vocab_size + 1, watchdog=False,
    )
    m.load()
    m._params = jax.device_put(params)
    dp = DataPlane()
    dp.register(m)
    try:
        # fake pre-restart load: the autoscaler/gateway would read these
        dp.inflight["m"] = 5
        m._inflight = 3
        m.engine.overlap["decode_gap_ms"] = 42.0
        old = m.engine
        old.poison(RuntimeError("test trip"))  # the watchdog's order
        m.restart_engine()
        old.stop()  # joins the (unwedged) old scheduler thread
        assert dp.inflight["m"] == 0
        assert m._inflight == 0
        # fresh engine: decode-gap EWMA restarts cold
        assert m.engine.overlap["decode_gap_ms"] == 0.0
        # a poisoned request unwinding its finally-release cannot push
        # the admission count negative after the reset
        m._release(2)
        assert m._inflight == 0
        dp.reset_load_signals("m")
        assert dp.inflight["m"] == 0
    finally:
        dp.unregister("m")


# ---------------------------------------- activator gauges (satellite)


def test_activator_exports_autoscaler_gauges():
    from kubeflow_tpu.gateway.activator import Activator

    async def run():
        kicked = []
        act = Activator(timeout_s=5.0, scale_up=kicked.append)

        async def parked():
            await act.wait("svc-g")

        t = asyncio.ensure_future(parked())
        await asyncio.sleep(0.01)
        assert _metric(
            "kft_gateway_activator_queue_depth", service="svc-g"
        ) == 1
        assert _metric(
            "kft_gateway_activator_cold_episode", service="svc-g"
        ) == 1
        assert kicked == ["svc-g"]
        act.notify("svc-g")
        await t
        assert _metric(
            "kft_gateway_activator_queue_depth", service="svc-g"
        ) == 0
        assert _metric(
            "kft_gateway_activator_cold_episode", service="svc-g"
        ) == 0

    asyncio.run(run())


# ------------------------------------------------- autoscaler control loop


def test_autoscaler_ticks_actuate_and_export_metrics():
    async def run():
        t = [0.0]

        class Actuator:
            def __init__(self):
                self.n = 1
                self.calls = []

            def current(self):
                return self.n

            async def scale_to(self, n):
                self.calls.append(n)
                self.n = n

        box = {"sig": ServiceSignals(inflight=6.0)}

        async def signals():
            return box["sig"]

        act = Actuator()
        asc = ServingAutoscaler(clock=lambda: t[0])
        asc.add_service(
            "svc-a",
            KPAConfig(
                target=1.0, min_replicas=0, max_replicas=8,
                stable_window_s=10.0, panic_window_s=2.0,
                scale_to_zero_grace_s=4.0,
            ),
            signals,
            act,
        )
        ups = _metric(
            "kft_autoscaler_scale_events_total",
            service="svc-a", direction="up",
        )
        t[0] = 1.0
        r = await asc.tick_service("svc-a")
        assert r.desired == 6 and act.calls == [6] and r.panic
        assert _metric(
            "kft_autoscaler_desired_replicas", service="svc-a"
        ) == 6
        assert _metric("kft_autoscaler_panic_mode", service="svc-a") == 1
        assert _metric(
            "kft_autoscaler_scale_events_total",
            service="svc-a", direction="up",
        ) == ups + 1
        # idle long past the stable window: panic exits, windows drain,
        # grace expires → rate-limited march down to zero
        box["sig"] = ServiceSignals()
        for step in range(8):
            t[0] = 20.0 + 5.0 * step
            await asc.tick_service("svc-a")
        assert act.n == 0
        assert asc.view()["svc-a"]["current"] == 0
        # the activator kick path: parked demand scales from zero NOW
        box["sig"] = ServiceSignals(activator_depth=2.0)
        t[0] = 70.0
        asc.kick("svc-a")
        await asyncio.sleep(0.05)  # kick's out-of-band tick task
        assert act.n >= 1

    asyncio.run(run())


# -------------------------------------------- manifest + dashboard wiring


def test_gateway_manifest_autoscaling_section_and_validation():
    from kubeflow_tpu.gateway.server import GatewayConfig

    cfg = GatewayConfig.from_manifest({
        "kind": "InferenceGateway",
        "metadata": {"name": "edge"},
        "spec": {
            "services": [{
                "name": "m",
                "autoscaling": {
                    "minReplicas": 0, "maxReplicas": 3, "target": 2,
                    "panicThreshold": 1.5,
                    "replicaCommand": ["python", "-m", "kubeflow_tpu",
                                       "serve", "-f", "isvc.yaml",
                                       "--http-port", "0",
                                       "--port-file", "{port_file}"],
                },
            }],
        },
    })
    auto = cfg.autoscaling["m"]
    kpa = KPAConfig.from_manifest(auto)
    assert kpa.min_replicas == 0 and kpa.max_replicas == 3
    assert kpa.target == 2.0 and kpa.panic_threshold == 1.5
    assert auto["replicaCommand"][0] == "python"
    with pytest.raises(ValueError, match="replicaCommand"):
        GatewayConfig.from_manifest({
            "kind": "InferenceGateway",
            "spec": {"services": [{
                "name": "m",
                "autoscaling": {"replicaCommand": "not-an-argv-list"},
            }]},
        })


def test_dashboard_autoscaler_api_and_metrics():
    async def run():
        from aiohttp.test_utils import TestClient, TestServer

        from kubeflow_tpu.platform.dashboard import DashboardServer

        class Act:
            def current(self):
                return 2

        asc = ServingAutoscaler()

        async def signals():
            return ServiceSignals(inflight=1.0)

        asc.add_service("m", KPAConfig(max_replicas=4), signals, Act())
        await asc.tick_service("m")  # populate the recommendation gauges
        dash = DashboardServer(cluster=None, autoscaler=asc)
        async with TestClient(TestServer(dash._make_app())) as client:
            body = await (await client.get("/api/autoscaler")).json()
            assert body["m"]["current"] == 2
            assert body["m"]["config"]["max_replicas"] == 4
            assert body["m"]["desired"] is not None
            # the shared registry rides the dashboard's /metrics too —
            # the satellite's "surfaced on gateway and dashboard" half
            resp = await client.get("/metrics")
            assert resp.status == 200
            text = await resp.text()
            assert 'kft_autoscaler_desired_replicas{service="m"}' in text
        assert DashboardServer(cluster=None).autoscaler_view() == {}

    asyncio.run(run())


# ------------------------------------------------------------ e2e helpers


class _SlowModel(Model):
    """Echo with latency: concurrency accumulates so the scraped
    kft_server_inflight signal actually moves during a burst."""

    def __init__(self, name: str, delay_s: float):
        super().__init__(name)
        self.delay_s = delay_s
        self.ready = True

    async def __call__(self, payload, headers=None):
        await asyncio.sleep(self.delay_s)
        n = len(payload.get("instances", [0]))
        return {"predictions": ["ok"] * n}


async def _test_server(ms: ModelServer):
    from aiohttp.test_utils import TestServer

    srv = TestServer(ms.build_app())
    await srv.start_server()
    return srv, f"http://127.0.0.1:{srv.port}"


# --------------------------------------------------- chaos acceptance e2e


@pytest.mark.chaos
def test_burst_scales_1_3_1_then_zero_with_no_client_failures():
    """ISSUE 11 acceptance, part 1: an open-loop burst against the REAL
    gateway + real ModelServer replicas panics the autoscaler 1→3, the
    quiet stable window brings it back down through 1 to zero, and the
    first request after scale-to-zero is served via activator buffering —
    every client request 200 throughout."""
    from aiohttp.test_utils import TestClient, TestServer as _TS

    from kubeflow_tpu.gateway.router import ServiceRoute
    from kubeflow_tpu.gateway.server import GatewayConfig, InferenceGateway

    async def run():
        replicas = []

        async def launch(index):
            ms = ModelServer([_SlowModel("m", delay_s=0.3)], http_port=0)
            srv, url = await _test_server(ms)
            replicas.append(srv)

            async def stop():
                await srv.close()

            return url, stop

        gw_box = {}
        asc = ServingAutoscaler(tick_interval_s=0.1)
        gw = InferenceGateway(
            GatewayConfig(
                probe_interval_s=0.25,
                activation_timeout_s=20.0,
                routes=[ServiceRoute(name="m")],
            ),
            scale_up=asc.kick,
        )
        gw_box["gw"] = gw
        fleet = ReplicaFleet("m", launch, pool=gw.pool)
        source = GatewaySignalSource(gw, "m")
        asc.add_service(
            "m",
            KPAConfig(
                target=2.0, min_replicas=0, max_replicas=3,
                stable_window_s=2.5, panic_window_s=0.5,
                panic_threshold=1.5, max_scale_down_rate=2.0,
                scale_to_zero_grace_s=1.0,
            ),
            source,
            fleet,
        )
        await fleet.scale_to(1)
        client = TestClient(_TS(gw.build_app()))
        await client.start_server()
        asc.start()
        statuses = []
        peak = [0]

        async def one(i):
            r = await client.post(
                "/v1/models/m:predict",
                json={"instances": [[i]]},
                headers={"x-request-id": f"burst-{i}"},
            )
            statuses.append(r.status)
            await r.release()

        async def watch_peak():
            while True:
                peak[0] = max(peak[0], fleet.current())
                await asyncio.sleep(0.02)

        watcher = asyncio.ensure_future(watch_peak())
        try:
            # open-loop burst: fixed arrival rate, no waiting on responses
            tasks = []
            for i in range(40):
                tasks.append(asyncio.ensure_future(one(i)))
                await asyncio.sleep(0.04)
            await asyncio.gather(*tasks)
            assert statuses == [200] * 40, statuses

            deadline = time.monotonic() + 20.0
            while peak[0] < 3 and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert peak[0] == 3, f"never scaled to 3 (peak {peak[0]})"

            # quiet: panic exits after the stable window, then the grace
            # window expires and the service reaches zero
            deadline = time.monotonic() + 30.0
            while fleet.current() > 0 and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert fleet.current() == 0, fleet.current()
            assert _metric(
                "kft_autoscaler_desired_replicas", service="m"
            ) == 0

            # scale-from-zero: the request parks in the activator, the
            # cold-episode kick relaunches a replica, the flush serves it
            acts0 = _metric("kft_gateway_activations_total", service="m")
            r = await client.post(
                "/v1/models/m:predict", json={"instances": [[99]]},
                headers={"x-request-id": "cold-99"},
            )
            assert r.status == 200, await r.text()
            await r.release()
            assert fleet.current() == 1
            assert _metric(
                "kft_gateway_activations_total", service="m"
            ) == acts0 + 1
            assert _metric(
                "kft_autoscaler_scale_events_total",
                service="m", direction="up",
            ) >= 2
            assert _metric(
                "kft_autoscaler_scale_events_total",
                service="m", direction="down",
            ) >= 1
        finally:
            watcher.cancel()
            await asc.stop()
            await client.close()
            await source.close()
            await fleet.close()

    asyncio.run(run())


@pytest.mark.chaos
def test_ring_remap_prefix_kv_transfer_recovers_hit_rate():
    """ISSUE 11 acceptance, part 2: after a scale-up remaps the hash
    ring, the cold replica has already pulled the prefix entries it now
    owns from the previous owner — a remapped prompt lands a prefix HIT
    on it (kft_engine_prefix_hits_total) with 16 prompt tokens reused
    instead of re-prefilled, and its token stream is byte-identical."""
    import aiohttp
    import jax

    from kubeflow_tpu.serve.engine import LMEngineModel
    from kubeflow_tpu.serve.model import BucketSpec

    cfg, _, params = _tiny_lm()

    async def run():
        stops = []

        async def launch(index):
            m = LMEngineModel(
                "m", None, config=cfg, max_batch=4, chunk_steps=2,
                buckets=BucketSpec(batch_sizes=(1,), seq_lens=(32,)),
                max_new_tokens=4, eos_id=cfg.vocab_size + 1,
                watchdog=False, prefix_cache_entries=32,
            )
            m.load()
            # identical weights on every replica — transferred KV is
            # only valid if peers computed it with the same parameters
            m._params = jax.device_put(params)
            m.engine.stop()
            m.engine = m._make_engine().start()
            ms = ModelServer([m], http_port=0)
            srv, url = await _test_server(ms)

            async def stop():
                await srv.close()
                m.unload()

            stops.append(stop)
            return url, stop

        fleet = ReplicaFleet("m", launch, model="m")
        session = aiohttp.ClientSession()

        async def predict(url, ids):
            async with session.post(
                f"{url}/v1/models/m:predict",
                json={"instances": [{"input_ids": ids}]},
            ) as r:
                assert r.status == 200, await r.text()
                return (await r.json())["predictions"][0]

        async def metrics(url):
            async with session.get(f"{url}/metrics") as r:
                return parse_prom_text(await r.text())

        try:
            await fleet.scale_to(1)
            url_a = fleet.urls()[0]
            # distinct 17-token prompts → 12 stored 16-token entries on A
            prompts = [[2 + (7 * i + j) % 60 for j in range(17)]
                       for i in range(12)]
            outs_a = {}
            for i, p in enumerate(prompts):
                outs_a[i] = await predict(url_a, p)
            m_a = await metrics(url_a)
            assert metric_sum(m_a, "kft_engine_prefix_entries") == 12

            # scale up: the fleet pulls B's ring share from A BEFORE B
            # takes traffic
            await fleet.scale_to(2)
            url_b = next(u for u in fleet.urls() if u != url_a)
            ring = HashRing(tuple(sorted((url_a, url_b))))
            owned_by_b = [
                i for i, p in enumerate(prompts)
                if ring.pick(prefix_affinity_key(p[:16])) == url_b
            ]
            assert owned_by_b, "no prompt remapped to B — ring fixture"
            m_b = await metrics(url_b)
            imported = metric_sum(m_b, "kft_engine_prefix_imported_total")
            assert imported == len(owned_by_b)
            assert fleet.stats["kv_entries_moved"] == len(owned_by_b)
            assert _metric(
                "kft_autoscaler_kv_transfers_total", service="m"
            ) >= len(owned_by_b)

            # remapped prompts served by B: prefix HITS on transferred
            # KV, identical tokens, no full re-prefill
            for i in owned_by_b:
                out_b = await predict(url_b, prompts[i])
                assert out_b == outs_a[i], (out_b, outs_a[i])
            m_b = await metrics(url_b)
            hits = metric_sum(m_b, "kft_engine_prefix_hits_total")
            reused = metric_sum(
                m_b, "kft_engine_prefix_tokens_reused_total"
            )
            assert hits == len(owned_by_b)
            assert reused == 16 * len(owned_by_b)

            # scale-down evacuates the leaver's entries to the survivor
            await fleet.scale_to(1)
            assert fleet.urls() == [url_a]
            assert fleet.stats["stopped"] == 1
        finally:
            await fleet.close()
            await session.close()

    asyncio.run(run())
