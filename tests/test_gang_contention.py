"""Gang contention at BASELINE config-4 scale (VERDICT r3 missing #8;
SURVEY.md §3.4, §7 hard part 1): 16 parallel gang-scheduled trials against
a constrained multi-slice fleet — queueing order, priority, topology-aware
claims, no deadlock/starvation.

Two tiers, mirroring the reference's strategy (§4): scheduler-level
table tests (the envtest analog — pure control plane, no processes) and an
e2e run of 16 real JAXJob subprocesses through LocalCluster.
"""

import time

import pytest

from kubeflow_tpu.orchestrator.gang import GangScheduler, PodGroup
from kubeflow_tpu.orchestrator.resources import Fleet


def _group(uid, *, chips=4, topo="2x2", n=1, queue="default", priority=0):
    return PodGroup(
        job_uid=uid,
        requests=[(f"worker-{i}", chips, topo, "v5e") for i in range(n)],
        queue=queue,
        priority=priority,
    )


# ------------------------------------------------------------------ #
# scheduler-level (envtest analog)
# ------------------------------------------------------------------ #


def test_16_trials_on_4_slices_fifo_no_starvation():
    """16 single-worker 2x2 gangs vs 4 slices: exactly 4 in flight, FIFO
    admission, every gang eventually runs (no starvation, no deadlock)."""
    sched = GangScheduler(Fleet.homogeneous(4, "2x2"))
    for i in range(16):
        g = _group(f"t{i:02d}")
        g.enqueued_at = time.time() + i * 1e-3  # deterministic FIFO order
        sched.enqueue(g)

    admitted_order = []
    rounds = 0
    while len(admitted_order) < 16:
        batch = sched.try_schedule()
        assert len(batch) <= 4
        for g in batch:
            # topology-aware claim: a 2x2 request takes a whole 2x2 slice
            claim = next(iter(g.claims.values()))
            assert claim.chips == 4
            admitted_order.append(g.job_uid)
        # whoever is running finishes; capacity frees for the next wave
        for g in batch:
            sched.cancel(g.job_uid)
        rounds += 1
        assert rounds <= 16, "scheduler stopped admitting — deadlock"
    assert admitted_order == sorted(admitted_order), "FIFO order violated"
    assert sched.pending_count() == 0


def test_priority_admits_before_earlier_fifo():
    """A later-enqueued high-priority gang is admitted before earlier
    normal-priority gangs once capacity frees (Volcano priority semantics)."""
    sched = GangScheduler(Fleet.homogeneous(1, "2x2"))
    first = _group("first")
    first.enqueued_at = time.time()
    sched.enqueue(first)
    assert [g.job_uid for g in sched.try_schedule()] == ["first"]
    # fleet now full; two more arrive — low first, then high priority
    low = _group("low")
    low.enqueued_at = time.time() + 0.001
    high = _group("high", priority=10)
    high.enqueued_at = time.time() + 0.002
    sched.enqueue(low)
    sched.enqueue(high)
    assert sched.try_schedule() == []  # nothing fits yet
    sched.cancel("first")
    assert [g.job_uid for g in sched.try_schedule()] == ["high"]
    sched.cancel("high")
    assert [g.job_uid for g in sched.try_schedule()] == ["low"]


def test_blocked_large_gang_not_starved_by_backfill():
    """Head-of-line blocking: a 4-slice gang at the queue head must not be
    starved by a stream of 1-slice gangs behind it."""
    sched = GangScheduler(Fleet.homogeneous(4, "2x2"))
    hold = _group("hold", n=2)  # occupies 2 slices
    hold.enqueued_at = time.time()
    sched.enqueue(hold)
    assert [g.job_uid for g in sched.try_schedule()] == ["hold"]

    big = _group("big", n=4)  # needs ALL 4 slices — blocked while hold runs
    big.enqueued_at = time.time() + 0.001
    sched.enqueue(big)
    for i in range(8):
        small = _group(f"small{i}")
        small.enqueued_at = time.time() + 0.002 + i * 1e-3
        sched.enqueue(small)
    # 2 slices are free and the smalls would fit, but the blocked big gang
    # holds the line: admitting them would starve it forever
    assert sched.try_schedule() == []
    sched.cancel("hold")
    admitted = [g.job_uid for g in sched.try_schedule()]
    assert admitted[0] == "big", admitted


def test_queues_are_independent():
    """A blocked gang in one queue must not block another queue."""
    sched = GangScheduler(Fleet.homogeneous(2, "2x2"))
    blocked = _group("blocked", n=4, queue="research")  # can never fit
    sched.enqueue(blocked)
    prod = _group("prod", queue="prod")
    sched.enqueue(prod)
    assert [g.job_uid for g in sched.try_schedule()] == ["prod"]


def test_topology_mismatch_never_admits_but_times_out():
    sched = GangScheduler(Fleet.homogeneous(4, "2x2"))
    g = _group("impossible", chips=16, topo="4x4")
    g.timeout_seconds = 0.01
    sched.enqueue(g)
    assert sched.try_schedule() == []
    time.sleep(0.02)
    expired = sched.timed_out()
    assert [e.job_uid for e in expired] == ["impossible"]
    assert sched.pending_count() == 0


# ------------------------------------------------------------------ #
# e2e: 16 real jobs through the cluster (kind-e2e analog)
# ------------------------------------------------------------------ #


@pytest.mark.slow
def test_16_parallel_jobs_contend_for_4_slices_e2e(tmp_path):
    import sys

    from kubeflow_tpu.orchestrator import (
        JobSpec,
        LocalCluster,
        ReplicaSpec,
        TPURequest,
    )
    from kubeflow_tpu.orchestrator.envwire import WiringConfig
    from kubeflow_tpu.orchestrator.spec import RunPolicy, SchedulingPolicy

    cluster = LocalCluster(
        fleet=Fleet.homogeneous(4, "2x2"),
        wiring=WiringConfig(platform="cpu_sim", devices_per_worker=4),
        base_dir=str(tmp_path),
        resync_period=0.05,
    )
    with cluster:
        uids = {}
        for i in range(16):
            priority = 10 if i >= 14 else 0  # last two submitted are urgent
            job = JobSpec(
                name=f"trial{i:02d}",
                replicas={
                    "worker": ReplicaSpec(
                        replicas=1,
                        command=(
                            sys.executable, "-c",
                            "import time; time.sleep(0.4); print('done')",
                        ),
                        tpu=TPURequest(chips=4),
                    )
                },
                run_policy=RunPolicy(
                    scheduling=SchedulingPolicy(priority=priority)
                ),
            )
            uids[i] = cluster.submit(job)
            time.sleep(0.01)  # deterministic enqueue order

        peak_running = 0
        deadline = time.time() + 120
        start_times: dict[int, float] = {}
        while time.time() < deadline:
            phases = {i: cluster.status(u).phase for i, u in uids.items()}
            running = [i for i, p in phases.items() if p == "Running"]
            peak_running = max(peak_running, len(running))
            for i in running:
                start_times.setdefault(i, time.time())
            if all(p == "Succeeded" for p in phases.values()):
                break
            assert not any(p == "Failed" for p in phases.values()), phases
            time.sleep(0.02)
        phases = {i: cluster.status(u).phase for i, u in uids.items()}
        assert all(p == "Succeeded" for p in phases.values()), phases
        # constrained fleet: never more than 4 gangs hold slices at once
        assert peak_running <= 4, peak_running
        # the two priority trials must start before the tail of the
        # default-priority queue they jumped
        tail_defaults = [start_times[i] for i in (12, 13)]
        urgent = [start_times[i] for i in (14, 15)]
        assert max(urgent) < max(tail_defaults), (start_times,)
