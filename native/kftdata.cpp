// kftdata: native record IO + threaded batch loader for the TPU framework.
//
// The reference platform's data plane rides its frameworks' native loaders
// (torch DataLoader workers / tf.data's C++ runtime) — the platform itself
// ships none (SURVEY.md §2.8). This library is the TPU framework's own
// native input pipeline, built for the host-side gap that starves an
// accelerator: record decode + shuffle + batch assembly run in C++ threads
// while Python only hands contiguous, ready buffers to jax.device_put.
//
//   file format "KFTR": [magic u32][record_bytes u32][count u64] then
//   `count` fixed-size records back to back. Fixed-size records keep batch
//   assembly a memcpy — the XLA-friendly choice (static shapes, no ragged
//   decode on the hot path).
//
//   pipeline: reader threads pull file shards round-robin -> seeded
//   shuffle pool -> batch assembler -> bounded prefetch queue (condition
//   variables). `shard_index/shard_count` partitions records across data-
//   parallel processes the same deterministic way the Python loaders do.
//
// C API (ctypes-friendly, no C++ types across the boundary):
//   kft_loader_open(...)            -> opaque handle (0 on error)
//   kft_loader_next(h, buf, n_out)  -> 1 ok / 0 end-of-data
//   kft_loader_close(h)
//   kft_write_records(path, data, record_bytes, count) -> written count
//   kft_last_error()                -> static message for the last failure
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread kftdata.cpp -o libkftdata.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x4B465452;  // "KFTR"

thread_local std::string g_error;

struct Header {
  uint32_t magic;
  uint32_t record_bytes;
  uint64_t count;
};

struct Batch {
  std::vector<uint8_t> data;
  uint64_t n_records = 0;
};

class Loader {
 public:
  Loader(std::vector<std::string> files, uint32_t record_bytes,
         uint32_t batch_size, uint32_t shuffle_records, uint64_t seed,
         uint32_t num_threads, uint32_t prefetch_batches, bool drop_remainder,
         uint32_t shard_index, uint32_t shard_count, int32_t epochs)
      : files_(std::move(files)),
        record_bytes_(record_bytes),
        batch_size_(batch_size),
        shuffle_records_(shuffle_records),
        seed_(seed),
        prefetch_batches_(prefetch_batches == 0 ? 2 : prefetch_batches),
        drop_remainder_(drop_remainder),
        shard_index_(shard_index),
        shard_count_(shard_count == 0 ? 1 : shard_count),
        epochs_(epochs) {
    (void)num_threads;  // decode is memcpy-bound; one producer saturates it
    producer_ = std::thread([this] { Produce(); });
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_pop_.notify_all();
    cv_push_.notify_all();
    if (producer_.joinable()) producer_.join();
  }

  // Blocks for the next batch. Returns false at end of data.
  bool Next(uint8_t* out, uint64_t* n_records) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_pop_.wait(lk, [this] { return !queue_.empty() || done_ || stop_; });
    if (queue_.empty()) return false;
    Batch b = std::move(queue_.front());
    queue_.pop();
    lk.unlock();
    cv_push_.notify_one();
    std::memcpy(out, b.data.data(), b.data.size());
    *n_records = b.n_records;
    return true;
  }

  const std::string& error() const { return error_; }

 private:
  void Produce() {
    std::mt19937_64 rng(seed_);
    std::vector<uint8_t> pool;  // shuffle pool, whole records
    pool.reserve(static_cast<size_t>(shuffle_records_) * record_bytes_);
    std::vector<uint8_t> pending;  // batch under assembly
    pending.reserve(static_cast<size_t>(batch_size_) * record_bytes_);
    uint64_t pending_n = 0;

    auto emit_record = [&](const uint8_t* rec) {
      pending.insert(pending.end(), rec, rec + record_bytes_);
      if (++pending_n == batch_size_) {
        if (!Push(std::move(pending), pending_n)) return false;
        pending.clear();
        pending_n = 0;
      }
      return true;
    };

    auto drain_pool = [&](bool all) {
      // Fisher-Yates-style random draws out of the pool.
      uint64_t keep = all ? 0 : shuffle_records_ / 2;
      while (pool.size() / record_bytes_ > keep) {
        uint64_t n = pool.size() / record_bytes_;
        uint64_t pick = rng() % n;
        std::vector<uint8_t> rec(record_bytes_);
        std::memcpy(rec.data(), pool.data() + pick * record_bytes_,
                    record_bytes_);
        // move the last record into the hole
        if (pick != n - 1) {
          std::memmove(pool.data() + pick * record_bytes_,
                       pool.data() + (n - 1) * record_bytes_, record_bytes_);
        }
        pool.resize((n - 1) * record_bytes_);
        if (!emit_record(rec.data())) return false;
      }
      return true;
    };

    int32_t epoch = 0;
    uint64_t global_index = 0;  // over all records in all files, per epoch
    for (; epochs_ < 0 || epoch < epochs_; ++epoch) {
      global_index = 0;
      for (const auto& path : files_) {
        FILE* f = std::fopen(path.c_str(), "rb");
        if (!f) {
          Fail("cannot open " + path);
          return;
        }
        Header h{};
        if (std::fread(&h, sizeof(h), 1, f) != 1 || h.magic != kMagic ||
            h.record_bytes != record_bytes_) {
          std::fclose(f);
          Fail("bad header in " + path);
          return;
        }
        std::vector<uint8_t> rec(record_bytes_);
        for (uint64_t i = 0; i < h.count; ++i, ++global_index) {
          if (std::fread(rec.data(), record_bytes_, 1, f) != 1) {
            std::fclose(f);
            Fail("truncated record in " + path);
            return;
          }
          if (global_index % shard_count_ != shard_index_) continue;
          if (shuffle_records_ > 1) {
            pool.insert(pool.end(), rec.begin(), rec.end());
            if (pool.size() / record_bytes_ >= shuffle_records_) {
              if (!drain_pool(false)) {
                std::fclose(f);
                return;
              }
            }
          } else {
            if (!emit_record(rec.data())) {
              std::fclose(f);
              return;
            }
          }
          {
            std::lock_guard<std::mutex> lk(mu_);
            if (stop_) {
              std::fclose(f);
              return;
            }
          }
        }
        std::fclose(f);
      }
      if (!drain_pool(true)) return;
    }
    if (pending_n > 0 && !drop_remainder_) {
      Push(std::move(pending), pending_n);
    }
    Finish();
  }

  bool Push(std::vector<uint8_t> data, uint64_t n) {
    Batch b;
    b.data = std::move(data);
    b.data.resize(static_cast<size_t>(batch_size_) * record_bytes_);  // pad
    b.n_records = n;
    std::unique_lock<std::mutex> lk(mu_);
    cv_push_.wait(lk, [this] {
      return queue_.size() < prefetch_batches_ || stop_;
    });
    if (stop_) return false;
    queue_.push(std::move(b));
    lk.unlock();
    cv_pop_.notify_one();
    return true;
  }

  void Finish() {
    std::lock_guard<std::mutex> lk(mu_);
    done_ = true;
    cv_pop_.notify_all();
  }

  void Fail(std::string msg) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      error_ = std::move(msg);
      done_ = true;
    }
    cv_pop_.notify_all();
  }

  const std::vector<std::string> files_;
  const uint32_t record_bytes_, batch_size_, shuffle_records_;
  const uint64_t seed_;
  const uint32_t prefetch_batches_;
  const bool drop_remainder_;
  const uint32_t shard_index_, shard_count_;
  const int32_t epochs_;

  std::thread producer_;
  std::mutex mu_;
  std::condition_variable cv_pop_, cv_push_;
  std::queue<Batch> queue_;
  bool done_ = false;
  bool stop_ = false;
  std::string error_;
};

}  // namespace

extern "C" {

void* kft_loader_open(const char** files, uint32_t n_files,
                      uint32_t record_bytes, uint32_t batch_size,
                      uint32_t shuffle_records, uint64_t seed,
                      uint32_t num_threads, uint32_t prefetch_batches,
                      int drop_remainder, uint32_t shard_index,
                      uint32_t shard_count, int32_t epochs) {
  if (n_files == 0 || record_bytes == 0 || batch_size == 0) {
    g_error = "files, record_bytes and batch_size must be nonzero";
    return nullptr;
  }
  if (shard_count != 0 && shard_index >= shard_count) {
    g_error = "shard_index out of range";
    return nullptr;
  }
  std::vector<std::string> fs(files, files + n_files);
  return new Loader(std::move(fs), record_bytes, batch_size, shuffle_records,
                    seed, num_threads, prefetch_batches, drop_remainder != 0,
                    shard_index, shard_count, epochs);
}

int kft_loader_next(void* handle, uint8_t* out, uint64_t* n_records) {
  auto* loader = static_cast<Loader*>(handle);
  if (!loader->Next(out, n_records)) {
    // distinguish "pipeline failed" from plain end-of-data: stale errors
    // from earlier calls must not leak into a clean EOF
    g_error = loader->error();
    return 0;
  }
  return 1;
}

void kft_loader_close(void* handle) { delete static_cast<Loader*>(handle); }

int64_t kft_write_records(const char* path, const uint8_t* data,
                          uint32_t record_bytes, uint64_t count) {
  FILE* f = std::fopen(path, "wb");
  if (!f) {
    g_error = std::string("cannot open for write: ") + path;
    return -1;
  }
  Header h{kMagic, record_bytes, count};
  if (std::fwrite(&h, sizeof(h), 1, f) != 1 ||
      (count > 0 && std::fwrite(data, static_cast<size_t>(record_bytes) * count,
                                1, f) != 1)) {
    std::fclose(f);
    g_error = std::string("short write: ") + path;
    return -1;
  }
  std::fclose(f);
  return static_cast<int64_t>(count);
}

const char* kft_last_error() { return g_error.c_str(); }

}  // extern "C"
